"""Builtin console — the HTTP debug pages every server carries.

Counterpart of src/brpc/builtin/ (registered in server.cpp:468-563):
/status /vars /flags /health /connections /index /version /brpc_metrics
/protobufs /bthreads /sockets /rpcz /list — served by the HTTP protocol's
router. Each handler: (server, http_request) -> (status, content_type, body).
"""
from __future__ import annotations

import json
import time

from brpc_tpu import bvar
from brpc_tpu.builtin.hotspots import _ProfWindow
from brpc_tpu.butil import flags as flags_mod

# one native capture window at a time (the recorder is a single shared
# resource like the profilers): the second concurrent /rpc_dump?seconds=
# request gets 503 + Retry-After instead of a stop/start collision
_rpc_dump_window = _ProfWindow(
    30.0, "rpc_dump busy: another /rpc_dump capture window is running\n")


def _status_handler(server, req):
    """/status: server + per-method stats (builtin/status_service.cpp)."""
    lines = [
        f"version: brpc_tpu/{_version()}",
        f"non-service: builtin",
        f"uptime: {time.time() - (server.start_time or time.time()):.0f}s",
        f"listen: {server.listen_endpoint}",
        f"connection_count: {server.connection_count()}",
        f"service_count: {server.service_count}",
        "",
    ]
    for full, st in sorted(server.method_statuses().items()):
        lines.append(st.describe())
    # native-runtime section (per-protocol counters + tail latency from
    # the C++ stat cells) when native traffic exists
    try:
        from brpc_tpu.bvar.native_vars import native_status_lines

        lines += native_status_lines()
    except Exception:
        pass
    return 200, "text/plain", "\n".join(lines) + "\n"


def _vars_handler(server, req):
    """/vars: every exposed bvar; /vars/<name> filters; ?chart=1 renders
    an SVG trend of a windowed var (the in-browser series charts of
    builtin/vars_service.cpp + the flot bundle, dependency-free)."""
    parts = [p for p in req.path.split("/") if p]
    needle = parts[1] if len(parts) > 1 else None
    if needle and req.query.get("chart"):
        return _var_chart(needle, req)
    out = []
    for name, value in bvar.dump_exposed():
        if needle and needle not in name:
            continue
        if hasattr(value, "average"):
            value = f"avg={value.average:.3f} num={value.num}"
        out.append(f"{name} : {value}")
    return 200, "text/plain", "\n".join(out) + "\n"


def _var_chart(name: str, req):
    """Inline-SVG sparkline of a Window/PerSecond var's per-second series;
    ?format=json returns the raw points."""
    from xml.sax.saxutils import escape

    from brpc_tpu.bvar.variable import find_exposed

    var = find_exposed(name)
    if var is None:
        # the /vars listing matches substrings; accept a UNIQUE substring
        # match here too so a listed name can be charted directly
        matches = [n for n, _ in bvar.dump_exposed() if name in n]
        if len(matches) == 1:
            var = find_exposed(matches[0])
            name = matches[0]
    if var is None:
        return 404, "text/plain", f"no such var: {name}\n"
    series_fn = getattr(var, "series", None)
    if series_fn is None:
        return 400, "text/plain", f"{name} is not a windowed var\n"
    points = series_fn()
    if req.query.get("format") == "json":
        body = json.dumps({"var": name,
                           "points": [[round(t, 3), v]
                                      for t, v in points]})
        return 200, "application/json", body + "\n"
    w, h, pad = 480, 120, 6
    if len(points) < 2:
        svg = (f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
               f'height="{h}"><text x="10" y="20">{escape(name)}: '
               f'collecting samples...</text></svg>')
        return 200, "image/svg+xml", svg
    values = [v for _, v in points]
    vmin, vmax = min(values), max(values)
    spread = (vmax - vmin) or 1.0
    t0, t1 = points[0][0], points[-1][0]
    tspan = (t1 - t0) or 1.0
    coords = " ".join(
        f"{pad + (t - t0) / tspan * (w - 2 * pad):.1f},"
        f"{h - pad - (v - vmin) / spread * (h - 2 * pad):.1f}"
        for t, v in points)
    svg = (
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" height="{h}">'
        f'<rect width="{w}" height="{h}" fill="#fcfcfc" stroke="#ccc"/>'
        f'<polyline points="{coords}" fill="none" stroke="#3366cc" '
        f'stroke-width="1.5"/>'
        f'<text x="8" y="14" font-size="11" fill="#333">{escape(name)} '
        f'(last {len(points)}s: min={vmin:.6g} max={vmax:.6g})</text>'
        f'</svg>')
    return 200, "image/svg+xml", svg


def _flags_handler(server, req):
    """/flags list; /flags/<name>?setvalue=v live-edits a reloadable flag
    (builtin/flags_service.cpp + reloadable_flags.h)."""
    parts = [p for p in req.path.split("/") if p]
    if len(parts) > 1:
        name = parts[1]
        setvalue = req.query.get("setvalue")
        if setvalue is not None:
            if flags_mod.set_flag(name, setvalue):
                return 200, "text/plain", f"{name} set to {setvalue}\n"
            return 403, "text/plain", f"cannot set {name}\n"
        try:
            f = flags_mod.flag(name)
        except KeyError:
            return 404, "text/plain", f"no such flag: {name}\n"
        return 200, "text/plain", (
            f"{f.name}={f.value} (default={f.default}) "
            f"{'[reloadable]' if f.reloadable else ''} {f.help}\n"
        )
    out = []
    for name, f in sorted(flags_mod.all_flags().items()):
        mark = " (R)" if f.reloadable else ""
        out.append(f"{name}={f.value}{mark}  # {f.help}")
    return 200, "text/plain", "\n".join(out) + "\n"


def _health_handler(server, req):
    return 200, "text/plain", "OK\n"


def _connections_handler(server, req):
    """/connections (builtin/connections_service.cpp): the Python socket
    pool's table plus one row per live NATIVE socket — byte/message
    counters with windowed per-second rates (bvar/window.py), write-stack
    depth (unwritten bytes), sniffed protocol and owning dispatcher."""
    lines = ["remote_side          |socket_id          |state"]
    for sock in server.list_connections():
        lines.append(
            f"{str(sock.remote_side):21s}|{sock.socket_id:<19d}|"
            f"{'failed' if sock.failed() else 'ok'}"
        )
    try:
        from brpc_tpu import native
        from brpc_tpu.bvar.native_vars import (
            connection_rates,
            prune_connection_windows,
        )

        rows = native.conn_snapshot() if native.available() else []
    except Exception:
        rows = []
    if rows:
        lines.append("")
        lines.append("native sockets:")
        lines.append(
            "remote_side          |socket_id          |proto   |side  |"
            "disp|in_bytes(/s)        |out_bytes(/s)       |in_msg  |"
            "out_msg |rd_sys  |wr_sys  |unwritten |mem_bytes")
        prune_connection_windows(r["sock_id"] for r in rows)
        total_mem = 0
        for r in sorted(rows, key=lambda r: r["sock_id"]):
            rates = connection_rates(r["sock_id"])
            in_cell = f"{r['in_bytes']}({rates['in_Bps']:,.0f}/s)"
            out_cell = f"{r['out_bytes']}({rates['out_Bps']:,.0f}/s)"
            total_mem += r.get("mem_bytes", 0)
            lines.append(
                f"{r['remote'] or '?':21s}|{r['sock_id']:<19d}|"
                f"{r['protocol']:8s}|"
                f"{'srv' if r['server_side'] else 'cli':6s}|"
                f"{r['disp_idx']:<4d}|"
                f"{in_cell:<20s}|{out_cell:<20s}|"
                f"{r['in_msgs']:<8d}|{r['out_msgs']:<8d}|"
                f"{r['read_calls']:<8d}|{r['write_calls']:<8d}|"
                f"{r['unwritten_bytes']:<10d}|{r.get('mem_bytes', 0)}")
        # where the bytes sit at scale: per-socket buffered memory
        # (write stack + read buffer + reorder windows) summed, so the
        # 20k-connection page answers "what does a connection cost"
        lines.append(f"native socket buffered memory: {total_mem} bytes "
                     f"across {len(rows)} sockets")
    return 200, "text/plain", "\n".join(lines) + "\n"


def _index_handler(server, req):
    pages = sorted(server._builtin_handlers.keys())
    services = sorted(server.method_statuses().keys())
    body = ("brpc_tpu server console\n\npages:\n"
            + "\n".join(f"  /{p}" for p in pages)
            + "\n\nmethods:\n"
            + "\n".join(f"  /{m.replace('.', '/')}" for m in services)
            + "\n")
    return 200, "text/plain", body


def _version_handler(server, req):
    return 200, "text/plain", f"brpc_tpu/{_version()}\n"


def _metrics_handler(server, req):
    """/brpc_metrics: Prometheus exposition
    (builtin/prometheus_metrics_service.cpp)."""
    return 200, "text/plain; version=0.0.4", bvar.dump_prometheus()


def _fleet_handler(server, req):
    """/fleet: the fleet observatory rollup — merged methods (quantiles
    off MERGED log2 buckets), per-member breaker/lame-duck/overload
    state, SLO burn rates. ?backend=ip:port drills into one member;
    ?json=1 dumps the rollup; ?trace_id=<hex> fans find_trace across
    the swarm."""
    try:
        from brpc_tpu import fleet
    except ImportError:
        return 200, "text/plain", "fleet: module not loaded\n"
    tid = req.query.get("trace_id")
    if tid:
        parts = []
        for obs in fleet.active_observatories():
            parts.append(obs.stitched_trace(int(tid, 16)))
        return 200, "text/plain", ("".join(parts)
                                   or "no fleet observatory running\n")
    return 200, "text/plain", fleet.render_fleet_page(req.query)


def _protobufs_handler(server, req):
    """/protobufs: message schemas in use (builtin/protobufs_service.cpp)."""
    seen = {}
    for (svc, method), (obj, minfo, st) in server._methods.items():
        for cls in (minfo.request_class, minfo.response_class):
            try:
                seen[cls.DESCRIPTOR.full_name] = str(cls.DESCRIPTOR.file.name)
            except AttributeError:
                seen[cls.__name__] = "<python>"
    body = "\n".join(f"{k}  ({v})" for k, v in sorted(seen.items()))
    return 200, "text/plain", body + "\n"


def _bthreads_handler(server, req):
    """/bthreads: scheduler stats (builtin/bthreads_service.cpp)."""
    from brpc_tpu.bthread import get_task_control

    tc = get_task_control()
    lines = [
        f"workers: {len(tc.groups)}",
        f"queued: {tc._queued_count()}",
        f"switches: {tc._nswitch_var.get_value()}",
        f"finished: {tc._finished_var.get_value()}",
    ]
    for g in tc.groups:
        lines.append(
            f"  group {g.group_id}: rq={len(g._rq)} remote={len(g._remote_rq)}"
            f" bound={len(g._bound_rq)} nswitch={g.nswitch}"
        )
    return 200, "text/plain", "\n".join(lines) + "\n"


def _sockets_handler(server, req):
    """/sockets: socket pool introspection (builtin/sockets_service.cpp)."""
    from brpc_tpu.rpc.socket import Socket

    pool = Socket._get_pool()
    return 200, "text/plain", f"socket_slots: {pool.size()}\n"


def _rpc_dump_status_body():
    """Status text of /rpc_dump: native recorder status + capture files
    on disk + the Python-lane rpc_dump flags (one pane for both)."""
    import os

    lines = ["traffic flight recorder (rpc_dump)", ""]
    st = None
    try:
        from brpc_tpu import native

        if native.available():
            st = native.dump_status()
    except Exception:
        st = None
    if st is None:
        lines.append("native recorder: unavailable (no native runtime)")
    else:
        lines.append(
            f"native recorder: {'RUNNING' if st['running'] else 'stopped'}"
            f"  sample_every={st['every']}  seed={st['seed']}")
        lines.append(
            f"  window: samples={st['samples']} written={st['written']} "
            f"bytes={st['bytes']} drops={st['drops']} "
            f"oversize={st['oversize']} rotations={st['rotations']}")
        lines.append(
            f"  config: dir={st['dir'] or '(unset)'} "
            f"max_file_bytes={st['max_file_bytes']} "
            f"generations={st['generations']} "
            f"max_payload={st['max_payload']}")
        if st["dir"]:
            try:
                names = sorted(n for n in os.listdir(st["dir"])
                               if n.endswith(".rio"))
            except OSError:
                names = []
            lines.append(f"  capture files ({len(names)}):")
            for n in names:
                try:
                    sz = os.path.getsize(os.path.join(st["dir"], n))
                except OSError:
                    sz = 0
                lines.append(f"    {n}  {sz} bytes")
    lines.append("")
    # the flags are defined by the module that owns the python lane
    from brpc_tpu.rpc import rpc_dump as _rpc_dump_mod  # noqa: F401

    lines.append(
        f"python lane: -rpc_dump={flags_mod.get_flag('rpc_dump')} "
        f"-rpc_dump_dir={flags_mod.get_flag('rpc_dump_dir')} "
        f"-rpc_dump_sample_every="
        f"{flags_mod.get_flag('rpc_dump_sample_every')}")
    lines.append("")
    lines.append("GET /rpc_dump?seconds=N[&every=M][&dir=PATH] arms a "
                 "bounded native capture window; replay the files with "
                 "`python tools/rpc_replay.py --native`.")
    return "\n".join(lines) + "\n"


def _rpc_dump_handler(server, req):
    """/rpc_dump: the traffic flight recorder's console page — status,
    sample rate, capture files, drop counts; ?seconds=N arms a bounded
    native capture window (serialized by the shared one-window guard:
    a concurrent window request gets 503 + Retry-After, the /hotspots/*
    discipline)."""
    seconds = req.query.get("seconds")
    if not seconds:
        return 200, "text/plain", _rpc_dump_status_body()
    try:
        from brpc_tpu import native

        if not native.available():
            return 200, "text/plain", "native runtime unavailable\n"
    except Exception as e:
        return 200, "text/plain", f"native runtime unavailable: {e}\n"
    try:
        every = int(req.query.get("every", "1") or 1)
    except ValueError:
        return 400, "text/plain", "every must be an integer\n"
    directory = req.query.get("dir") or flags_mod.get_flag("rpc_dump_dir")

    def _capture_window(s):
        rc = native.dump_start(directory, every=max(1, every))
        if rc == -1:
            # an embedder owns the recorder: report, don't steal the
            # window (the sample_native rc == -1 discipline)
            return ("recorder already armed by the embedder:\n\n"
                    + _rpc_dump_status_body())
        if rc != 0:
            return f"could not start capture under {directory!r}\n"
        time.sleep(s)
        native.dump_stop()
        return _rpc_dump_status_body()

    try:
        window_s = float(seconds)
    except ValueError:
        return 400, "text/plain", "seconds must be a number\n"
    return _rpc_dump_window.run(window_s, _capture_window)


def _heap_handler(server, req):
    """/heap: live allocations by site — Python lane via tracemalloc;
    /heap/native reports the NATIVE allocators (iobuf block pool, socket
    slabs, fiber stacks, shm arenas...) from the nat_res ledger's
    sampled allocation-site profiler, which tracemalloc cannot see
    (ISSUE 14 — the reference's tcmalloc-backed /heap builtin)."""
    from brpc_tpu.builtin import hotspots, profilers

    parts = [p for p in req.path.split("/") if p]
    if len(parts) > 1 and parts[1] == "native":
        try:
            seconds = float(req.query.get("seconds", "0") or 0)
        except ValueError:
            return 400, "text/plain", "seconds must be a number\n"
        flat = req.query.get("flat", "") not in ("", "0")
        return hotspots._res_prof_window.run(
            max(0.1, seconds),
            lambda s: hotspots.heap_native(seconds, flat=flat))
    return 200, "text/plain", profilers.heap_profile()


def _growth_handler(server, req):
    """/growth: allocation growth since profiling start — Python lane
    via tracemalloc; /growth/native diffs native live-bytes-by-site
    against the baseline (?seconds=N re-baselines and reports exactly
    that window's growth)."""
    from brpc_tpu.builtin import hotspots, profilers

    parts = [p for p in req.path.split("/") if p]
    if len(parts) > 1 and parts[1] == "native":
        try:
            seconds = float(req.query.get("seconds", "0") or 0)
        except ValueError:
            return 400, "text/plain", "seconds must be a number\n"
        return hotspots._res_prof_window.run(
            max(0.1, seconds),
            lambda s: hotspots.growth_native(seconds))
    return 200, "text/plain", profilers.growth_profile()


def _rpcz_handler(server, req):
    """/rpcz: recent spans (builtin/rpcz_service.cpp); filled by the rpcz
    module once tracing is enabled."""
    try:
        from brpc_tpu.rpcz import describe_recent_spans

        return 200, "text/plain", describe_recent_spans(req.query)
    except ImportError:
        return 200, "text/plain", "rpcz: tracing module not loaded\n"


def _list_handler(server, req):
    """/list: service listing as JSON (builtin/list_service.cpp)."""
    out = {}
    for (svc, method) in server._methods:
        out.setdefault(svc, []).append(method)
    return 200, "application/json", json.dumps(out, indent=1) + "\n"


def _vlog_handler(server, req):
    """/vlog: logging sites and levels, live-editable with
    ?setlevel=<logger>=<LEVEL> (builtin/vlog_service.cpp's role for the
    Python logging tree)."""
    import logging

    setlevel = req.query.get("setlevel")
    if setlevel:
        name, sep, level = setlevel.partition("=")
        if not sep:
            return 400, "text/plain", "setlevel wants logger=LEVEL\n"
        try:
            logging.getLogger(name).setLevel(level.upper())
        except ValueError as e:
            return 400, "text/plain", f"{e}\n"
        return 200, "text/plain", f"{name} set to {level.upper()}\n"
    lines = ["logger                                   | effective level"]
    root = logging.getLogger()
    lines.append(f"{'<root>':41s}| "
                 f"{logging.getLevelName(root.getEffectiveLevel())}")
    for name in sorted(logging.root.manager.loggerDict):
        logger = logging.getLogger(name)
        lines.append(f"{name:41s}| "
                     f"{logging.getLevelName(logger.getEffectiveLevel())}")
    return 200, "text/plain", "\n".join(lines) + "\n"


def _dir_handler(server, req):
    """/dir/<path>: browse the server's filesystem
    (builtin/dir_service.cpp — a debug console page, same trust model)."""
    import os
    import stat

    rel = req.path[len("/dir"):] or "/"
    path = rel if rel.startswith("/") else "/" + rel
    if not os.path.exists(path):
        return 404, "text/plain", f"no such path: {path}\n"
    if os.path.isdir(path):
        lines = [f"{path}:"]
        try:
            for name in sorted(os.listdir(path)):
                full = os.path.join(path, name)
                try:
                    st = os.stat(full)
                    kind = "d" if stat.S_ISDIR(st.st_mode) else "-"
                    lines.append(f"{kind} {st.st_size:>12d}  {name}")
                except OSError:
                    lines.append(f"? {'?':>12s}  {name}")
        except PermissionError:
            return 403, "text/plain", f"permission denied: {path}\n"
        return 200, "text/plain", "\n".join(lines) + "\n"
    try:
        with open(path, "rb") as f:
            body = f.read(1 << 20)  # bounded, like the reference's page
    except OSError as e:
        return 403, "text/plain", f"{e}\n"
    return 200, "application/octet-stream", body


def _ids_handler(server, req):
    """/ids?id=N: bthread_id introspection (builtin/ids_service.cpp)."""
    from brpc_tpu.bthread import id as bthread_id

    id_q = req.query.get("id")
    if id_q:
        try:
            idv = int(id_q)
        except ValueError:
            return 400, "text/plain", "id must be an integer\n"
        slot, version = bthread_id._resolve(idv)
        if slot is None:
            return 200, "text/plain", f"id {idv}: unknown slot\n"
        valid = bthread_id._valid(slot, version)
        return 200, "text/plain", (
            f"id {idv}: version={version} first_version="
            f"{slot.first_version} range={slot.range} "
            f"locked={slot.locked} destroyed={slot.destroyed} "
            f"valid={valid} pending_errors={len(slot.pending_errors)}\n")
    with bthread_id._registry_lock:
        total = len(bthread_id._slots)
        live = sum(1 for s in bthread_id._slots.values() if not s.destroyed)
        locked = sum(1 for s in bthread_id._slots.values() if s.locked)
    return 200, "text/plain", (
        f"id_slots: {total}\nlive: {live}\nlocked: {locked}\n"
        "use /ids?id=N for one id\n")


def _version():
    import brpc_tpu

    return brpc_tpu.__version__


def attach_console(server):
    from brpc_tpu.builtin.hotspots import (
        hotspots_handler,
        pprof_handler,
        threads_handler,
    )

    server._builtin_handlers = {
        "hotspots": hotspots_handler,
        "pprof": pprof_handler,
        "threads": threads_handler,
        "status": _status_handler,
        "vars": _vars_handler,
        "flags": _flags_handler,
        "health": _health_handler,
        "connections": _connections_handler,
        "index": _index_handler,
        "version": _version_handler,
        "brpc_metrics": _metrics_handler,
        "protobufs": _protobufs_handler,
        "bthreads": _bthreads_handler,
        "sockets": _sockets_handler,
        "heap": _heap_handler,
        "growth": _growth_handler,
        "rpc_dump": _rpc_dump_handler,
        "rpcz": _rpcz_handler,
        "fleet": _fleet_handler,
        "list": _list_handler,
        "vlog": _vlog_handler,
        "dir": _dir_handler,
        "ids": _ids_handler,
    }
    bvar.expose_flags_as_bvars()
