"""brpc_tpu.builtin — the HTTP debug console services
(/status /vars /flags /connections /rpcz /brpc_metrics ...), counterpart of
src/brpc/builtin/ (registered by server.cpp:468-563).

Services register here; the HTTP protocol serves them once it lands.
"""
from __future__ import annotations


def register_builtin_services(server) -> None:
    """Attach builtin service handlers to the server (AddBuiltinServices,
    server.cpp:949). Until the HTTP protocol lands this records the server
    for the console; the HTTP layer routes /status etc. to handlers."""
    try:
        from brpc_tpu.builtin.console import attach_console

        attach_console(server)
    except ImportError:
        pass
