"""mcpack2pb — mcpack codec with a protobuf front-end.

Counterpart of /root/reference/src/mcpack2pb/ (field_type.h, parser,
serializer, generator): mcpack is Baidu's TLV wire format; the reference
generates code making protobuf messages its front-end. Here the codec maps
Python values (and protobuf messages via their descriptors) to/from mcpack
v2 bytes.

Wire layout (field_type.h:28-78, serializer.cpp:29-88):
  FieldFixedHead { u8 type, u8 name_size }            + name + value
  FieldShortHead { u8 type|0x80, u8 name_size, u8 value_size }
  FieldLongHead  { u8 type, u8 name_size, u32 value_size }   (little-endian)
  OBJECT/ARRAY   = FieldLongHead + name + ItemsHead{u32 count} + items
  names and strings are NUL-terminated; name_size counts the NUL.
"""
from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple, Union

FIELD_OBJECT = 0x10
FIELD_ARRAY = 0x20
FIELD_STRING = 0x50
FIELD_BINARY = 0x60
FIELD_INT8 = 0x11
FIELD_INT16 = 0x12
FIELD_INT32 = 0x14
FIELD_INT64 = 0x18
FIELD_UINT8 = 0x21
FIELD_UINT16 = 0x22
FIELD_UINT32 = 0x24
FIELD_UINT64 = 0x28
FIELD_BOOL = 0x31
FIELD_FLOAT = 0x44
FIELD_DOUBLE = 0x48
FIELD_NULL = 0x61
SHORT_MASK = 0x80
FIXED_MASK = 0x0F

_INT_PACK = {
    FIELD_INT8: "<b", FIELD_INT16: "<h", FIELD_INT32: "<i",
    FIELD_INT64: "<q", FIELD_UINT8: "<B", FIELD_UINT16: "<H",
    FIELD_UINT32: "<I", FIELD_UINT64: "<Q",
}


def _encode_field(name: str, value) -> bytes:
    nbytes = name.encode() + b"\x00" if name else b""
    if isinstance(value, bool):
        return bytes([FIELD_BOOL, len(nbytes)]) + nbytes + (b"\x01" if value else b"\x00")
    if isinstance(value, int):
        for t in (FIELD_INT32, FIELD_INT64):
            try:
                packed = struct.pack(_INT_PACK[t], value)
                return bytes([t, len(nbytes)]) + nbytes + packed
            except struct.error:
                continue
        packed = struct.pack("<Q", value)
        return bytes([FIELD_UINT64, len(nbytes)]) + nbytes + packed
    if isinstance(value, float):
        return bytes([FIELD_DOUBLE, len(nbytes)]) + nbytes + struct.pack("<d", value)
    if isinstance(value, str):
        raw = value.encode() + b"\x00"
        if len(raw) <= 255:
            return bytes([FIELD_STRING | SHORT_MASK, len(nbytes),
                          len(raw)]) + nbytes + raw
        return bytes([FIELD_STRING, len(nbytes)]) + struct.pack(
            "<I", len(raw)) + nbytes + raw
    if isinstance(value, (bytes, bytearray)):
        raw = bytes(value)
        if len(raw) <= 255:
            return bytes([FIELD_BINARY | SHORT_MASK, len(nbytes),
                          len(raw)]) + nbytes + raw
        return bytes([FIELD_BINARY, len(nbytes)]) + struct.pack(
            "<I", len(raw)) + nbytes + raw
    if value is None:
        return bytes([FIELD_NULL, len(nbytes)]) + nbytes + b"\x00"
    if isinstance(value, dict):
        items = b"".join(_encode_field(k, v) for k, v in value.items())
        body = struct.pack("<I", len(value)) + items
        return bytes([FIELD_OBJECT, len(nbytes)]) + struct.pack(
            "<I", len(body)) + nbytes + body
    if isinstance(value, (list, tuple)):
        items = b"".join(_encode_field("", v) for v in value)
        body = struct.pack("<I", len(value)) + items
        return bytes([FIELD_ARRAY, len(nbytes)]) + struct.pack(
            "<I", len(body)) + nbytes + body
    raise TypeError(f"mcpack cannot encode {type(value)}")


def dumps(obj: dict) -> bytes:
    """Top-level value is an OBJECT (as mcpack requests are)."""
    if not isinstance(obj, dict):
        raise TypeError("mcpack top-level must be a dict")
    return _encode_field("", obj)


# -- typed serializer primitives (serializer.cpp:29-88's put_* surface) -----
# Generated code (mcpack2pb_gen) calls these so each pb field gets its
# EXACT mcpack wire type, like the reference's generated put_int32/put_str
# calls — the reflective dict path above auto-sizes instead.

def _enc_typed_int(name: str, value: int, ftype: int) -> bytes:
    nbytes = name.encode() + b"\x00" if name else b""
    return bytes([ftype, len(nbytes)]) + nbytes + struct.pack(
        _INT_PACK[ftype], value)


def enc_int32(name: str, v: int) -> bytes:
    return _enc_typed_int(name, v, FIELD_INT32)


def enc_int64(name: str, v: int) -> bytes:
    return _enc_typed_int(name, v, FIELD_INT64)


def enc_uint32(name: str, v: int) -> bytes:
    return _enc_typed_int(name, v, FIELD_UINT32)


def enc_uint64(name: str, v: int) -> bytes:
    return _enc_typed_int(name, v, FIELD_UINT64)


def enc_bool(name: str, v: bool) -> bytes:
    nbytes = name.encode() + b"\x00" if name else b""
    return bytes([FIELD_BOOL, len(nbytes)]) + nbytes + (
        b"\x01" if v else b"\x00")


def enc_float(name: str, v: float) -> bytes:
    nbytes = name.encode() + b"\x00" if name else b""
    return bytes([FIELD_FLOAT, len(nbytes)]) + nbytes + struct.pack("<f", v)


def enc_double(name: str, v: float) -> bytes:
    nbytes = name.encode() + b"\x00" if name else b""
    return bytes([FIELD_DOUBLE, len(nbytes)]) + nbytes + struct.pack("<d", v)


def enc_str(name: str, v: str) -> bytes:
    return _encode_field(name, str(v))


def enc_bytes(name: str, v: bytes) -> bytes:
    return _encode_field(name, bytes(v))


def enc_object(name: str, fields) -> bytes:
    """fields: iterable of already-encoded member field bytes."""
    fields = list(fields)
    items = b"".join(fields)
    nbytes = name.encode() + b"\x00" if name else b""
    body = struct.pack("<I", len(fields)) + items
    return bytes([FIELD_OBJECT, len(nbytes)]) + struct.pack(
        "<I", len(body)) + nbytes + body


def enc_array(name: str, items_encoded) -> bytes:
    items_encoded = list(items_encoded)
    items = b"".join(items_encoded)
    nbytes = name.encode() + b"\x00" if name else b""
    body = struct.pack("<I", len(items_encoded)) + items
    return bytes([FIELD_ARRAY, len(nbytes)]) + struct.pack(
        "<I", len(body)) + nbytes + body


def _decode_field(data: bytes, pos: int) -> Tuple[str, object, int]:
    ftype = data[pos]
    short = bool(ftype & SHORT_MASK)
    base = ftype & ~SHORT_MASK
    name_size = data[pos + 1]
    if base in (FIELD_OBJECT, FIELD_ARRAY, FIELD_STRING, FIELD_BINARY) and not short:
        (value_size,) = struct.unpack_from("<I", data, pos + 2)
        head = 6
    elif short:
        value_size = data[pos + 2]
        head = 3
    else:  # fixed
        value_size = ftype & FIXED_MASK
        head = 2
    name_start = pos + head
    name = data[name_start:name_start + max(0, name_size - 1)].decode(
        "utf-8", "replace") if name_size else ""
    vpos = name_start + name_size
    raw = data[vpos:vpos + value_size]
    end = vpos + value_size
    if base == FIELD_STRING:
        return name, raw[:-1].decode("utf-8", "replace"), end
    if base == FIELD_BINARY:
        return name, bytes(raw), end
    if base == FIELD_BOOL:
        return name, bool(raw[0]), end
    if base in _INT_PACK:
        return name, struct.unpack(_INT_PACK[base], raw)[0], end
    if base == FIELD_DOUBLE:
        return name, struct.unpack("<d", raw)[0], end
    if base == FIELD_FLOAT:
        return name, struct.unpack("<f", raw)[0], end
    if base == FIELD_NULL:
        return name, None, end
    if base in (FIELD_OBJECT, FIELD_ARRAY):
        (count,) = struct.unpack_from("<I", data, vpos)
        ipos = vpos + 4
        if base == FIELD_OBJECT:
            out: Dict[str, object] = {}
            for _ in range(count):
                k, v, ipos = _decode_field(data, ipos)
                out[k] = v
            return name, out, end
        arr = []
        for _ in range(count):
            _, v, ipos = _decode_field(data, ipos)
            arr.append(v)
        return name, arr, end
    raise ValueError(f"unknown mcpack type {ftype:#x}")


def loads(data: bytes) -> dict:
    _, value, _ = _decode_field(data, 0)
    if not isinstance(value, dict):
        raise ValueError("mcpack top-level is not an object")
    return value


# -- protobuf front-end (the mcpack2pb generator's role) --------------------

def pb_to_mcpack(message) -> bytes:
    """Serialize a protobuf message as mcpack (field names as keys)."""
    return dumps(_pb_to_dict(message))


def mcpack_to_pb(data: bytes, message_class):
    """Parse mcpack into a protobuf message by field-name match."""
    obj = loads(data)
    msg = message_class()
    _dict_to_pb(obj, msg)
    return msg


def _is_repeated(field) -> bool:
    v = getattr(field, "is_repeated", None)
    if isinstance(v, bool):
        return v  # modern protobuf: a bool property
    try:
        return bool(v())  # older protobuf: a method
    except TypeError:
        return field.label == field.LABEL_REPEATED


def _pb_to_dict(message) -> dict:
    out = {}
    for field, value in message.ListFields():
        if _is_repeated(field):
            if field.type == field.TYPE_MESSAGE:
                out[field.name] = [_pb_to_dict(v) for v in value]
            else:
                out[field.name] = list(value)
        elif field.type == field.TYPE_MESSAGE:
            out[field.name] = _pb_to_dict(value)
        else:
            out[field.name] = value
    return out


def _dict_to_pb(obj: dict, msg):
    for field in msg.DESCRIPTOR.fields:
        if field.name not in obj:
            continue
        value = obj[field.name]
        if _is_repeated(field):
            target = getattr(msg, field.name)
            for item in value or []:
                if field.type == field.TYPE_MESSAGE:
                    _dict_to_pb(item, target.add())
                else:
                    target.append(item)
        elif field.type == field.TYPE_MESSAGE:
            _dict_to_pb(value, getattr(msg, field.name))
        else:
            setattr(msg, field.name, value)
