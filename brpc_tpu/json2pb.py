"""json2pb — JSON ⇄ protobuf conversion satellite.

Counterpart of /root/reference/src/json2pb/ (json_to_pb.h, pb_to_json.h):
the bridge the HTTP protocol uses to serve protobuf services as JSON REST
endpoints. Backed by google.protobuf.json_format with brpc-compatible
options (bytes as base64, enums as strings by default).
"""
from __future__ import annotations

from typing import Optional, Type

from google.protobuf import json_format


class Pb2JsonOptions:
    def __init__(self, bytes_to_base64: bool = True,
                 jsonify_empty_array: bool = False,
                 always_print_primitive_fields: bool = False,
                 enum_option_as_int: bool = False):
        self.bytes_to_base64 = bytes_to_base64
        self.jsonify_empty_array = jsonify_empty_array
        self.always_print_primitive_fields = always_print_primitive_fields
        self.enum_option_as_int = enum_option_as_int


def pb_to_json(message, options: Optional[Pb2JsonOptions] = None) -> str:
    """ProtoMessageToJson (pb_to_json.h)."""
    options = options or Pb2JsonOptions()
    return json_format.MessageToJson(
        message,
        preserving_proto_field_name=True,
        use_integers_for_enums=options.enum_option_as_int,
        always_print_fields_with_no_presence=options.always_print_primitive_fields,
    )


def json_to_pb(json_text: str, message_class: Type):
    """JsonToProtoMessage (json_to_pb.h); raises json_format.ParseError on
    malformed input."""
    msg = message_class()
    json_format.Parse(json_text, msg, ignore_unknown_fields=True)
    return msg


def json_to_pb_inplace(json_text: str, message) -> bool:
    try:
        json_format.Parse(json_text, message, ignore_unknown_fields=True)
        return True
    except json_format.ParseError:
        return False
