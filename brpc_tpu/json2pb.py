"""json2pb — JSON ⇄ protobuf conversion satellite.

Counterpart of /root/reference/src/json2pb/ (json_to_pb.{h,cpp},
pb_to_json.{h,cpp}, ~1.7 kLoC on rapidjson): the bridge the HTTP protocol
uses to serve protobuf services as JSON REST endpoints. This is a real
descriptor-walking codec, not a delegate: field iteration, type dispatch,
base64 bytes, map fields, enums by name or number, int64-as-string
tolerance, required-field checking with field paths in errors, and the
reference's Pb2JsonOptions/Json2PbOptions knobs.

Reference semantics implemented (json_to_pb.cpp / pb_to_json.cpp):
  * bytes ⇄ base64 (bytes_to_base64, default on)
  * enums as names by default, numbers with enum_option_as_int; parse
    accepts either form
  * map<K,V> fields ⇄ JSON objects with stringified keys
  * int64/uint64 parse from JSON numbers OR strings (JS precision escape)
  * unknown JSON fields ignored (the reference's default tolerance)
  * missing required proto2 fields fail with the field's path
  * jsonify_empty_array prints [] for unset repeated fields;
    always_print_primitive_fields prints proto3 defaults
"""
from __future__ import annotations

import base64
import json
import math
from typing import Optional, Type

from google.protobuf import descriptor as _desc

_FD = _desc.FieldDescriptor


class ParseError(ValueError):
    """Malformed JSON or JSON that cannot map onto the message."""


class Pb2JsonOptions:
    def __init__(self, bytes_to_base64: bool = True,
                 jsonify_empty_array: bool = False,
                 always_print_primitive_fields: bool = False,
                 enum_option_as_int: bool = False):
        self.bytes_to_base64 = bytes_to_base64
        self.jsonify_empty_array = jsonify_empty_array
        self.always_print_primitive_fields = always_print_primitive_fields
        self.enum_option_as_int = enum_option_as_int


class Json2PbOptions:
    def __init__(self, base64_to_bytes: bool = True,
                 allow_remaining_bytes_after_parsing: bool = False):
        self.base64_to_bytes = base64_to_bytes
        self.allow_remaining_bytes_after_parsing = (
            allow_remaining_bytes_after_parsing)


_INT_TYPES = {_FD.TYPE_INT32, _FD.TYPE_INT64, _FD.TYPE_UINT32,
              _FD.TYPE_UINT64, _FD.TYPE_FIXED32, _FD.TYPE_FIXED64,
              _FD.TYPE_SFIXED32, _FD.TYPE_SFIXED64, _FD.TYPE_SINT32,
              _FD.TYPE_SINT64}
_FLOAT_TYPES = {_FD.TYPE_DOUBLE, _FD.TYPE_FLOAT}
_INT_RANGES = {
    _FD.TYPE_INT32: (-(1 << 31), (1 << 31) - 1),
    _FD.TYPE_SINT32: (-(1 << 31), (1 << 31) - 1),
    _FD.TYPE_SFIXED32: (-(1 << 31), (1 << 31) - 1),
    _FD.TYPE_UINT32: (0, (1 << 32) - 1),
    _FD.TYPE_FIXED32: (0, (1 << 32) - 1),
    _FD.TYPE_INT64: (-(1 << 63), (1 << 63) - 1),
    _FD.TYPE_SINT64: (-(1 << 63), (1 << 63) - 1),
    _FD.TYPE_SFIXED64: (-(1 << 63), (1 << 63) - 1),
    _FD.TYPE_UINT64: (0, (1 << 64) - 1),
    _FD.TYPE_FIXED64: (0, (1 << 64) - 1),
}


def _is_repeated(field) -> bool:
    try:
        return field.is_repeated  # protobuf >= 5 property (no deprecation)
    except AttributeError:
        return field.label == _FD.LABEL_REPEATED


def _is_required(field) -> bool:
    try:
        return field.is_required
    except AttributeError:
        return field.label == _FD.LABEL_REQUIRED


def _is_map_field(field) -> bool:
    return (field.type == _FD.TYPE_MESSAGE and _is_repeated(field)
            and field.message_type.GetOptions().map_entry)


# ---------------------------------------------------------------------------
# pb -> json  (ProtoMessageToJson, pb_to_json.cpp)
# ---------------------------------------------------------------------------

def _scalar_to_json(field, value, opts: Pb2JsonOptions):
    if field.type == _FD.TYPE_BYTES:
        if opts.bytes_to_base64:
            return base64.b64encode(value).decode("ascii")
        return value.decode("latin-1")
    if field.type == _FD.TYPE_ENUM:
        if opts.enum_option_as_int:
            return int(value)
        ev = field.enum_type.values_by_number.get(value)
        return ev.name if ev is not None else int(value)
    if field.type in _FLOAT_TYPES:
        v = float(value)
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "Infinity" if v > 0 else "-Infinity"
        return v
    if field.type == _FD.TYPE_BOOL:
        return bool(value)
    if field.type in _INT_TYPES:
        return int(value)
    return value  # string


def _value_to_json(field, value, opts: Pb2JsonOptions):
    if field.type in (_FD.TYPE_MESSAGE, _FD.TYPE_GROUP):
        return _message_to_obj(value, opts)
    return _scalar_to_json(field, value, opts)


def _message_to_obj(msg, opts: Pb2JsonOptions) -> dict:
    out = {}
    desc = msg.DESCRIPTOR
    for field in desc.fields:
        name = field.name  # the reference keeps proto field names
        if _is_map_field(field):
            m = getattr(msg, name)
            if not m and not opts.jsonify_empty_array:
                continue
            vfield = field.message_type.fields_by_name["value"]
            kfield = field.message_type.fields_by_name["key"]
            if kfield.type == _FD.TYPE_BOOL:
                # JSON bool map keys are lowercase (reference/JS form)
                out[name] = {("true" if k else "false"):
                             _value_to_json(vfield, m[k], opts) for k in m}
            else:
                out[name] = {str(k): _value_to_json(vfield, m[k], opts)
                             for k in m}
        elif _is_repeated(field):
            seq = getattr(msg, name)
            if not seq and not opts.jsonify_empty_array:
                continue
            out[name] = [_value_to_json(field, v, opts) for v in seq]
        elif field.type in (_FD.TYPE_MESSAGE, _FD.TYPE_GROUP):
            if msg.HasField(name):
                out[name] = _message_to_obj(getattr(msg, name), opts)
        else:
            has = (msg.HasField(name) if field.has_presence
                   else bool(getattr(msg, name) != field.default_value))
            if has or opts.always_print_primitive_fields:
                out[name] = _scalar_to_json(field, getattr(msg, name), opts)
    return out


def pb_to_json(message, options: Optional[Pb2JsonOptions] = None) -> str:
    """ProtoMessageToJson (pb_to_json.h)."""
    options = options or Pb2JsonOptions()
    return json.dumps(_message_to_obj(message, options))


# ---------------------------------------------------------------------------
# json -> pb  (JsonToProtoMessage, json_to_pb.cpp)
# ---------------------------------------------------------------------------

def _parse_int(field, value, path: str) -> int:
    if isinstance(value, bool):
        raise ParseError(f"{path}: expected integer, got bool")
    if isinstance(value, str):
        try:
            value = int(value, 10)  # decimal only, like the reference
        except ValueError:
            raise ParseError(f"{path}: invalid integer string {value!r}")
    if isinstance(value, float):
        if not value.is_integer():
            raise ParseError(f"{path}: expected integer, got {value}")
        value = int(value)
    if not isinstance(value, int):
        raise ParseError(f"{path}: expected integer, got "
                         f"{type(value).__name__}")
    lo, hi = _INT_RANGES[field.type]
    if not lo <= value <= hi:
        raise ParseError(f"{path}: {value} out of range "
                         f"[{lo}, {hi}]")
    return value


def _parse_scalar(field, value, opts: Json2PbOptions, path: str):
    t = field.type
    if t == _FD.TYPE_BOOL:
        if isinstance(value, bool):
            return value
        if value in ("true", "True", 1):
            return True
        if value in ("false", "False", 0):
            return False
        raise ParseError(f"{path}: expected bool, got {value!r}")
    if t in _INT_TYPES:
        return _parse_int(field, value, path)
    if t in _FLOAT_TYPES:
        if isinstance(value, str):
            if value in ("NaN",):
                return float("nan")
            if value in ("Infinity", "inf"):
                return float("inf")
            if value in ("-Infinity", "-inf"):
                return float("-inf")
            try:
                return float(value)
            except ValueError:
                raise ParseError(f"{path}: invalid number {value!r}")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ParseError(f"{path}: expected number, got {value!r}")
        return float(value)
    if t == _FD.TYPE_STRING:
        if not isinstance(value, str):
            raise ParseError(f"{path}: expected string, got "
                             f"{type(value).__name__}")
        return value
    if t == _FD.TYPE_BYTES:
        if not isinstance(value, str):
            raise ParseError(f"{path}: expected base64 string")
        if opts.base64_to_bytes:
            try:
                return base64.b64decode(value)
            except Exception:
                raise ParseError(f"{path}: invalid base64")
        return value.encode("latin-1")
    if t == _FD.TYPE_ENUM:
        if isinstance(value, bool):
            raise ParseError(f"{path}: expected enum, got bool")
        if isinstance(value, int):
            # closed (proto2) enums reject unknown numbers at assignment;
            # surface that as a ParseError with the path instead
            if value not in field.enum_type.values_by_number:
                try:
                    closed = field.enum_type.is_closed()
                except AttributeError:
                    # older protobuf without is_closed(): proto3 enums are
                    # open (unknown numbers are preserved), proto2 closed —
                    # decide by syntax instead of rejecting everything
                    syntax = getattr(field.enum_type.file, "syntax",
                                     "proto2")
                    closed = syntax == "proto2"
                if closed:
                    raise ParseError(
                        f"{path}: {value} is not a value of "
                        f"{field.enum_type.full_name}")
            return value
        if isinstance(value, str):
            ev = field.enum_type.values_by_name.get(value)
            if ev is None:
                raise ParseError(
                    f"{path}: {value!r} is not a value of "
                    f"{field.enum_type.full_name}")
            return ev.number
        raise ParseError(f"{path}: expected enum name or number")
    raise ParseError(f"{path}: unsupported field type {t}")


def _fill_message(obj, msg, opts: Json2PbOptions, path: str):
    if not isinstance(obj, dict):
        raise ParseError(f"{path or '<root>'}: expected JSON object, got "
                         f"{type(obj).__name__}")
    desc = msg.DESCRIPTOR
    by_name = desc.fields_by_name
    by_json = {f.json_name: f for f in desc.fields}
    for key, value in obj.items():
        field = by_name.get(key) or by_json.get(key)
        if field is None:
            continue  # unknown fields ignored (reference tolerance)
        fpath = f"{path}.{field.name}" if path else field.name
        if value is None:
            continue  # JSON null clears nothing, like the reference
        if _is_map_field(field):
            if not isinstance(value, dict):
                raise ParseError(f"{fpath}: map field expects an object")
            kfield = field.message_type.fields_by_name["key"]
            vfield = field.message_type.fields_by_name["value"]
            target = getattr(msg, field.name)
            for k, v in value.items():
                if kfield.type == _FD.TYPE_BOOL:
                    pk = k.lower() == "true"
                elif kfield.type in _INT_TYPES:
                    pk = _parse_int(kfield, k, f"{fpath}[{k}]")
                else:
                    pk = k
                if vfield.type in (_FD.TYPE_MESSAGE, _FD.TYPE_GROUP):
                    _fill_message(v, target[pk], opts, f"{fpath}[{k}]")
                else:
                    parsed = _parse_scalar(vfield, v, opts,
                                           f"{fpath}[{k}]")
                    try:
                        target[pk] = parsed
                    except (ValueError, TypeError) as e:
                        raise ParseError(f"{fpath}[{k}]: {e}") from e
        elif _is_repeated(field):
            if not isinstance(value, list):
                raise ParseError(f"{fpath}: repeated field expects an array")
            target = getattr(msg, field.name)
            for i, item in enumerate(value):
                if field.type in (_FD.TYPE_MESSAGE, _FD.TYPE_GROUP):
                    _fill_message(item, target.add(), opts, f"{fpath}[{i}]")
                else:
                    parsed = _parse_scalar(field, item, opts,
                                           f"{fpath}[{i}]")
                    try:
                        target.append(parsed)
                    except (ValueError, TypeError) as e:
                        raise ParseError(f"{fpath}[{i}]: {e}") from e
        elif field.type in (_FD.TYPE_MESSAGE, _FD.TYPE_GROUP):
            _fill_message(value, getattr(msg, field.name), opts, fpath)
        else:
            parsed = _parse_scalar(field, value, opts, fpath)
            try:
                setattr(msg, field.name, parsed)
            except (ValueError, TypeError) as e:
                raise ParseError(f"{fpath}: {e}") from e
    # required-field check (proto2): the reference fails with the path
    for field in desc.fields:
        if _is_required(field) and not msg.HasField(field.name):
            fpath = f"{path}.{field.name}" if path else field.name
            raise ParseError(f"missing required field {fpath}")


def json_to_pb(json_text: str, message_class: Type,
               options: Optional[Json2PbOptions] = None):
    """JsonToProtoMessage (json_to_pb.h); raises ParseError on malformed
    input."""
    try:
        obj = json.loads(json_text)
    except json.JSONDecodeError as e:
        raise ParseError(f"invalid JSON: {e}") from e
    msg = message_class()
    _fill_message(obj, msg, options or Json2PbOptions(), "")
    return msg


def json_to_pb_inplace(json_text: str, message,
                       options: Optional[Json2PbOptions] = None) -> bool:
    try:
        obj = json.loads(json_text)
        _fill_message(obj, message, options or Json2PbOptions(), "")
        return True
    except (ParseError, json.JSONDecodeError):
        return False
