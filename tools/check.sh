#!/bin/sh
# tools/check.sh — the natcheck gate (also `make -C native check`).
#
# Runs the fast static passes first (concurrency lint + ABI/FFI contract
# + lock-order verification + refown ownership contracts + wiretrust
# wire-input taint — pure Python, seconds), then the lock-rank runtime
# validator (NAT_LOCKRANK build of the .so driven by the smoke — a rank
# inversion or a NatMutex held across a fiber switch aborts it), the
# refguard refcount validator (NAT_REFGUARD build: an unbalanced
# acquire/release tag pair aborts the smoke with the pair printed), and
# the strict UBSan smoke (-fno-sanitize-recover build: any undefined
# behaviour aborts); all skipped with a note when the toolchain is
# absent.
#
# NATCHECK_SLOW=1 adds the sanitizer lane (ASan+UBSan and TSan builds +
# smoke; several minutes of compile) and the dsched interleaving smoke.
# --soak (or NATCHECK_SOAK=1) additionally runs the full sanitizer soak
# matrix and writes native/SOAK.md (see tools/natcheck/soak.py).
# --refguard (or NATCHECK_REFGUARD=1) additionally runs the pytest
# native matrix against the refguard .so (BRPC_TPU_NATIVE_SO override)
# plus the deliberately-broken scenario that proves the guard fires
# (see tools/natcheck/refguard.py).
# --chaos (or NATCHECK_CHAOS=1) runs the fixed-seed fault-injection soak
# (C smoke + pytest native matrix under the documented NAT_FAULT spec)
# and writes native/CHAOS.md (see tools/natcheck/chaos.py).
# --replay (or NATCHECK_REPLAY=1) runs the flight-recorder round-trip
# gate: capture a seeded native run, restart the server fresh, replay
# the capture through the native replay client — zero failed RPCs,
# response-count parity, Python-reader byte identity (see
# tools/natcheck/replay.py).
# --fleet (or NATCHECK_FLEET=1) runs the fleet-observatory round: a
# live 3-server group behind a file naming feed, real traffic, then
# wire-native builtin.stats scrape -> exact histogram merge -> fleet
# quantiles -> SLO engine, end to end (see tools/natcheck/fleet.py).
# --fuzz (or NATCHECK_FUZZ=1) runs the bounded deterministic parser
# fuzz lane: every native/fuzz target (ASan+UBSan, fixed seed) over its
# committed corpus + regress inputs for NATCHECK_FUZZ_MS (default
# 2000ms) each; any crash or sanitizer report fails (see
# tools/natcheck/fuzzlane.py).
# --bench (or NATCHECK_BENCH=1) runs the perf regression gate: bench.py
# with the nat_prof flight recorder attached, a schema'd artifact
# (BENCH_latest.json), and a headline-lane diff against the last
# committed BENCH_r*.json — >15% regression on a stable lane hard-fails
# with that lane's profile attached (see tools/natcheck/benchgate.py).
# Exits nonzero on any finding.
set -u

cd "$(dirname "$0")/.."

PY="${PYTHON:-python3}"
RC=0

SOAK="${NATCHECK_SOAK:-0}"
CHAOS="${NATCHECK_CHAOS:-0}"
BENCH="${NATCHECK_BENCH:-0}"
REFGUARD="${NATCHECK_REFGUARD:-0}"
REPLAY="${NATCHECK_REPLAY:-0}"
FLEET="${NATCHECK_FLEET:-0}"
FUZZ="${NATCHECK_FUZZ:-0}"
for arg in "$@"; do
    case "$arg" in
        --soak) SOAK=1 ;;
        --chaos) CHAOS=1 ;;
        --bench) BENCH=1 ;;
        --refguard) REFGUARD=1 ;;
        --replay) REPLAY=1 ;;
        --fleet) FLEET=1 ;;
        --fuzz) FUZZ=1 ;;
    esac
done

# static passes first: they need no toolchain and must report even when
# the compile below cannot run
if [ "$SOAK" = "1" ] || [ "${NATCHECK_SLOW:-0}" = "1" ]; then
    "$PY" -m tools.natcheck lint abi lockorder refown wiretrust model san || RC=1
else
    "$PY" -m tools.natcheck lint abi lockorder refown wiretrust || RC=1
fi

# lock-rank runtime validator: build + drive the smoke under it
if command -v g++ >/dev/null 2>&1; then
    if make -C native lockrank >/dev/null 2>&1 &&
           native/nat_smoke_lockrank >/dev/null; then
        echo "natcheck: lockrank: clean"
    else
        echo "natcheck: lockrank: FAILED (rank inversion or smoke error)"
        RC=1
    fi
else
    echo "natcheck: lockrank: skipped (no g++)"
fi

# refcount-contract runtime validator (refown's twin): the NAT_REFGUARD
# build of the .so driven by the smoke — an unbalanced tag pair aborts
if command -v g++ >/dev/null 2>&1; then
    if make -C native refguard >/dev/null 2>&1 &&
           native/nat_smoke_refguard >/dev/null; then
        echo "natcheck: refguard: clean"
    else
        echo "natcheck: refguard: FAILED (unbalanced ref contract or smoke error)"
        RC=1
    fi
else
    echo "natcheck: refguard: skipped (no g++)"
fi

# strict UBSan smoke: -fno-sanitize-recover build — any undefined
# behaviour aborts the smoke instead of printing and continuing
if command -v g++ >/dev/null 2>&1; then
    if make -C native ubsan >/dev/null 2>&1 &&
           UBSAN_OPTIONS=print_stacktrace=1 native/nat_smoke_ubsan >/dev/null; then
        echo "natcheck: ubsan: clean"
    else
        echo "natcheck: ubsan: FAILED (undefined behaviour or smoke error)"
        RC=1
    fi
else
    echo "natcheck: ubsan: skipped (no g++)"
fi

if [ "$REFGUARD" = "1" ]; then
    "$PY" - <<'PYRG' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, refguard
findings = refguard.run()
print("natcheck: refguard lane: %s"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
PYRG
fi

if [ "$REPLAY" = "1" ]; then
    JAX_PLATFORMS=cpu "$PY" - <<'PYRP' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, replay
findings = replay.run()
print("natcheck: replay lane: %s"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
PYRP
fi

if [ "$SOAK" = "1" ]; then
    "$PY" - <<'EOF' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, soak
findings = soak.run()
print("natcheck: soak: %s (log: native/SOAK.md)"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
EOF
fi

if [ "$FLEET" = "1" ]; then
    JAX_PLATFORMS=cpu "$PY" - <<'PYFL' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, fleet
findings = fleet.run()
print("natcheck: fleet: %s"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
PYFL
fi

if [ "$FUZZ" = "1" ]; then
    "$PY" - <<'PYFZ' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, fuzzlane
findings = fuzzlane.run()
print("natcheck: fuzz: %s"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
PYFZ
fi

if [ "$BENCH" = "1" ]; then
    "$PY" - <<'EOF' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, benchgate
findings = benchgate.run()
print("natcheck: bench: %s (artifact: BENCH_latest.json)"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
EOF
fi

if [ "$CHAOS" = "1" ]; then
    "$PY" - <<'EOF' || RC=1
import sys
sys.path.insert(0, ".")
from tools.natcheck import print_findings, chaos
findings = chaos.run()
print("natcheck: chaos: %s (log: native/CHAOS.md)"
      % ("clean" if not findings else "%d finding(s)" % len(findings)))
print_findings(findings)
sys.exit(1 if findings else 0)
EOF
fi

exit $RC
