#!/bin/sh
# tools/check.sh — the natcheck gate (also `make -C native check`).
#
# Always runs the fast passes: concurrency lint + ABI/FFI contract check.
# With NATCHECK_SLOW=1 it adds the sanitizer lane (ASan+UBSan and TSan
# builds of the .so + smoke run under each; several minutes of compile).
# Exits nonzero on any finding.
set -eu

cd "$(dirname "$0")/.."

PY="${PYTHON:-python3}"

if [ "${NATCHECK_SLOW:-0}" = "1" ]; then
    exec "$PY" -m tools.natcheck lint abi san
else
    exec "$PY" -m tools.natcheck lint abi
fi
