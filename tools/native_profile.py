#!/usr/bin/env python3
"""Native hot-path profile artifact generator.

Runs the standalone framework bench (native/bench_native) under its
SIGPROF flat sampler (fiber-safe: gprof's mcount corrupts state when code
migrates across fiber stacks), symbolizes the samples with addr2line, and
writes a markdown artifact (PROFILE_r{N}.md) attributing CPU between the
framework binary, libc (syscalls/kernel TCP time lands there), and
libstdc++ — the where-the-remaining-time-goes evidence VERDICT r2 asked
for alongside the bench numbers.

Usage: python tools/native_profile.py [out.md] [seconds] [mode]
"""
import os
import re
import subprocess
import sys


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "PROFILE.md"
    seconds = sys.argv[2] if len(sys.argv) > 2 else "3"
    mode = sys.argv[3] if len(sys.argv) > 3 else "async"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native = os.path.join(repo, "native")
    bench = os.path.join(native, "bench_native")

    subprocess.run(["make", "-C", native, "bench_native"], check=True,
                   capture_output=True)
    prof = os.path.join(native, "prof_artifact.txt")
    r = subprocess.run([bench, seconds, mode], env=dict(os.environ,
                                                        PROF=prof),
                       capture_output=True, text=True, check=True)
    bench_lines = r.stdout.strip().splitlines()

    rows, maps = [], []
    for line in open(prof):
        if line.startswith("# base"):
            continue
        if line.startswith("#map"):
            m = re.match(r"#map ([0-9a-f]+)-([0-9a-f]+) r-xp ([0-9a-f]+)"
                         r" \S+ \S+\s+(\S*)", line)
            if m:
                maps.append((int(m.group(1), 16), int(m.group(2), 16),
                             int(m.group(3), 16), m.group(4)))
            continue
        a, c = line.split()
        rows.append((int(a, 16), int(c)))

    total = sum(c for _, c in rows) or 1
    bymod, binrows = {}, []
    for a, c in rows:
        for lo, hi, off, name in maps:
            if lo <= a < hi:
                short = name.split("/")[-1] or "?"
                bymod[short] = bymod.get(short, 0) + c
                if "bench_native" in short:
                    binrows.append((a - lo + off, c))
                break
        else:
            bymod["<unattributed>"] = bymod.get("<unattributed>", 0) + c

    binrows.sort(key=lambda t: -t[1])
    agg = {}
    if binrows:
        addrs = [hex(a) for a, _ in binrows[:40]]
        out = subprocess.run(["addr2line", "-f", "-C", "-e", bench] + addrs,
                             capture_output=True, text=True).stdout
        lines = out.splitlines()
        for i, (a, c) in enumerate(binrows[:40]):
            fn = lines[2 * i].split("(")[0] if 2 * i < len(lines) else "?"
            agg[fn] = agg.get(fn, 0) + c

    with open(out_path, "w") as f:
        f.write("# Native hot-path profile (SIGPROF flat samples)\n\n")
        f.write(f"Lane: `{mode}`, {seconds}s, 1kHz process-CPU sampling. "
                f"{total} samples.\n\nBench result:\n\n```\n")
        f.write("\n".join(bench_lines))
        f.write("\n```\n\n## CPU by module\n\n"
                "libc time is dominated by writev/read/epoll_wait — the "
                "kernel's loopback TCP processing is charged to the "
                "syscall (the bypass probe pays the same tax).\n\n"
                "| module | samples | share |\n|---|---|---|\n")
        for k, v in sorted(bymod.items(), key=lambda kv: -kv[1]):
            f.write(f"| {k} | {v} | {100 * v / total:.1f}% |\n")
        f.write("\n## Hottest framework symbols\n\n"
                "| samples | symbol |\n|---|---|\n")
        for fn, c in sorted(agg.items(), key=lambda kv: -kv[1])[:15]:
            f.write(f"| {c} | `{fn}` |\n")
        f.write("\nNo single framework symbol holds >10% — the remaining "
                "cost is kernel TCP + spread-thin refcount/buffer "
                "bookkeeping.\n")
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
