#!/usr/bin/env python
"""trackme_server — receives version pings
(tools/trackme_server counterpart). Counts pings per version at /trackme
and shows tallies at /status.

  python tools/trackme_server.py [--port 8877]
"""
from __future__ import annotations

import argparse
import json
import sys
import threading

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=8877)
    ap.add_argument("--notice", default="", help="notice pushed to pingers")
    args = ap.parse_args()

    from brpc_tpu import rpc

    counts = {}
    lock = threading.Lock()

    def trackme_handler(server, req):
        try:
            version = json.loads(req.body.to_bytes() or b"{}").get(
                "version", "unknown")
        except ValueError:
            version = "malformed"
        with lock:
            counts[version] = counts.get(version, 0) + 1
        body = {"ok": True}
        if args.notice:
            body["notice"] = args.notice
        return 200, "application/json", json.dumps(body)

    def tallies_handler(server, req):
        with lock:
            return 200, "application/json", json.dumps(counts, indent=1)

    srv = rpc.Server()
    assert srv.start(f"127.0.0.1:{args.port}") == 0
    srv._builtin_handlers["trackme"] = trackme_handler
    srv._builtin_handlers["tallies"] = tallies_handler
    print(f"trackme server on {srv.listen_endpoint} "
          f"(POST /trackme, GET /tallies)")
    srv.run_until_asked_to_quit()


if __name__ == "__main__":
    main()
