#!/usr/bin/env python
"""parallel_http — mass concurrent HTTP fetcher.

Counterpart of tools/parallel_http (/root/reference/tools/parallel_http/):
fetches many URLs concurrently and reports success/latency stats.

Usage:
  python tools/parallel_http.py --url-file urls.txt --concurrency 16
  python tools/parallel_http.py --url http://127.0.0.1:8000/status -n 100
"""
from __future__ import annotations

import argparse
import http.client
import sys
import threading
import time
from collections import deque
from urllib.parse import urlparse

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", help="single URL (with -n repeats)")
    ap.add_argument("-n", type=int, default=1, help="repeat count for --url")
    ap.add_argument("--url-file", help="file with one URL per line")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--timeout", type=float, default=5)
    args = ap.parse_args()

    urls = deque()
    if args.url_file:
        with open(args.url_file) as f:
            for line in f:
                line = line.strip()
                if line:
                    urls.append(line)
    elif args.url:
        for _ in range(args.n):
            urls.append(args.url)
    else:
        ap.error("need --url or --url-file")

    from brpc_tpu import bvar

    recorder = bvar.LatencyRecorder()
    ok = bvar.Adder()
    fail = bvar.Adder()
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if not urls:
                    return
                url = urls.popleft()
            u = urlparse(url)
            t0 = time.monotonic()
            try:
                conn = http.client.HTTPConnection(
                    u.hostname, u.port or 80, timeout=args.timeout)
                conn.request("GET", u.path or "/")
                r = conn.getresponse()
                r.read()
                conn.close()
                if 200 <= r.status < 400:
                    ok.update(1)
                    recorder.update((time.monotonic() - t0) * 1e6)
                else:
                    fail.update(1)
            except OSError:
                fail.update(1)

    t0 = time.monotonic()
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.monotonic() - t0
    total = ok.get_value() + fail.get_value()
    print(f"fetched={total} ok={ok.get_value()} failed={fail.get_value()} "
          f"in {dt:.1f}s ({total / dt:.1f}/s) "
          f"avg={recorder.latency():.0f}us "
          f"p99={recorder.latency_percentile(0.99):.0f}us")
    return 0


if __name__ == "__main__":
    sys.exit(main())
