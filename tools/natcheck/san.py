"""Sanitizer lane — build the .so under ASan+UBSan / TSan / UBSan-strict,
run the smoke.

``make -C native asan`` / ``make -C native tsan`` / ``make -C native
ubsan`` build the instrumented library plus ``nat_smoke_{kind}``, a
driver that links the .so through the public C API and exercises the
smoke subset: echo (native framework calls), http (native HTTP lane
round trips), stats (counters + span drain), clean exit (the PR-1
static-destructor class — the process must return 0 with runtime
threads still live).

The dedicated ubsan lane differs from the UBSan piggybacked on asan in
one load-bearing way: it is built ``-fno-sanitize-recover=undefined``,
so any undefined behaviour ABORTS the smoke instead of printing and
continuing — a hard gate rather than a log line.

Suppressions live in native/*.supp; every entry carries a comment saying
why it is a false positive. An unsuppressed report fails the lane.
"""
from __future__ import annotations

import os
import subprocess
from typing import List, Tuple

from tools.natcheck import Finding, REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")

_BAD_MARKERS = (
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "WARNING: ThreadSanitizer",
    "runtime error:",          # UBSan
    "SUMMARY: UndefinedBehaviorSanitizer",
)


def _env(kind: str) -> dict:
    env = dict(os.environ)
    if kind == "asan":
        env["ASAN_OPTIONS"] = "abort_on_error=0:exitcode=87"
        env["UBSAN_OPTIONS"] = "print_stacktrace=1"
        env["LSAN_OPTIONS"] = (
            "suppressions=%s" % os.path.join(NATIVE_DIR, "lsan.supp"))
    elif kind == "ubsan":
        env["UBSAN_OPTIONS"] = "print_stacktrace=1"
    else:
        env["TSAN_OPTIONS"] = (
            "suppressions=%s:halt_on_error=0:exitcode=86"
            % os.path.join(NATIVE_DIR, "tsan.supp"))
    return env


def build_and_run(kind: str, timeout: int = 900) -> Tuple[int, str]:
    """Build the `kind` lane ('asan'|'tsan'|'ubsan') and run its smoke
    binary. Returns (exit code, combined output); raises on build
    failure."""
    assert kind in ("asan", "tsan", "ubsan")
    subprocess.run(["make", "-C", NATIVE_DIR, kind], check=True,
                   capture_output=True, timeout=timeout)
    proc = subprocess.run(
        [os.path.join(NATIVE_DIR, f"nat_smoke_{kind}")],
        capture_output=True, timeout=timeout, env=_env(kind))
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    return proc.returncode, out


def run(kinds=("asan", "tsan", "ubsan")) -> List[Finding]:
    findings: List[Finding] = []
    for kind in kinds:
        try:
            rc, out = build_and_run(kind)
        except subprocess.CalledProcessError as e:
            findings.append(Finding(
                "san", f"{kind}-build", "native/Makefile",
                "build failed: " +
                (e.stderr or b"").decode(errors="replace")[-800:]))
            continue
        bad = [ln for ln in out.splitlines()
               if any(mk in ln for mk in _BAD_MARKERS)]
        if rc != 0 or bad:
            head = "; ".join(bad[:3]) if bad else out.strip()[-400:]
            findings.append(Finding(
                "san", kind, f"native/nat_smoke_{kind}",
                f"smoke exited rc={rc}: {head}"))
    return findings
