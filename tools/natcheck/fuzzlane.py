"""Fuzz lane — build the parser fuzz targets, run each for a bounded,
deterministic budget over its committed corpus, fail on any crash or
sanitizer report.

``make -C native fuzz`` builds one binary per hand-rolled wire parser
(native/fuzz/fuzz_*.cpp — tpu_std RpcMeta varints, HTTP/1, h2 frames,
HPACK, RESP, the recordio loader, the shm segment header), each linked
against the ASan+UBSan .so and driving the real production entry via
its nat_fuzz_* seam (native/src/nat_fuzz_entry.cpp). With clang++ on
PATH the binaries are libFuzzer (coverage-guided); otherwise the
bundled deterministic driver (native/fuzz/fuzz_driver_main.cpp) replays
the corpus and runs a fixed-seed mutation loop — either way this lane
passes ``-seed``/``--seed`` and a time budget so CI runs are
reproducible and bounded.

Inputs per target: ``native/fuzz/corpus/<name>/`` (structure-aware hand
seeds) plus ``native/fuzz/regress/<name>/`` (minimized crashers from
past findings, committed so they are re-fuzzed forever, not just
replayed — the fast replay gate is tests/test_fuzz_regress.py).

A nonzero exit or a sanitizer marker in the output is a finding. The
budget default (2s/target) keeps ``tools/check.sh --fuzz`` in CI
territory; crank NATCHECK_FUZZ_MS for a soak.
"""
from __future__ import annotations

import os
import subprocess
from typing import List

from tools.natcheck import Finding, REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")
FUZZ_DIR = os.path.join(NATIVE_DIR, "fuzz")

TARGETS = ("rpc_meta", "http", "h2", "redis", "hpack", "recordio",
           "shm_seg")

SEED = 20250806  # fixed: the lane must be reproducible run-to-run

_BAD_MARKERS = (
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",
    "SUMMARY: UndefinedBehaviorSanitizer",
    "SUMMARY: libFuzzer",
    "DEADLYSIGNAL",
)


def _is_libfuzzer(binary: str) -> bool:
    """libFuzzer binaries answer -help=1; the standalone driver rejects
    unknown flags with exit 2 and no libFuzzer banner."""
    try:
        proc = subprocess.run([binary, "-help=1"], capture_output=True,
                              timeout=30)
    except Exception:
        return False
    return b"libFuzzer" in proc.stdout + proc.stderr


def build(timeout: int = 900) -> None:
    """Build the asan .so + every fuzz binary (raises on failure)."""
    subprocess.run(["make", "-C", NATIVE_DIR, "fuzz"], check=True,
                   capture_output=True, timeout=timeout)


def run_target(name: str, budget_ms: int) -> "tuple[int, str]":
    """Run one target for budget_ms over corpus+regress; returns
    (exit code, combined output)."""
    binary = os.path.join(FUZZ_DIR, "bin", "fuzz_" + name)
    dirs = [d for d in (os.path.join(FUZZ_DIR, "corpus", name),
                        os.path.join(FUZZ_DIR, "regress", name))
            if os.path.isdir(d)]
    env = dict(os.environ)
    env["ASAN_OPTIONS"] = "abort_on_error=0:exitcode=87"
    env["UBSAN_OPTIONS"] = "print_stacktrace=1"
    env["LSAN_OPTIONS"] = (
        "suppressions=%s" % os.path.join(NATIVE_DIR, "lsan.supp"))
    if _is_libfuzzer(binary):
        secs = max(1, budget_ms // 1000)
        cmd = [binary, "-seed=%d" % SEED, "-max_total_time=%d" % secs,
               "-print_final_stats=0"] + dirs
    else:
        cmd = [binary, "--seed", str(SEED), "--budget-ms",
               str(budget_ms)] + dirs
    proc = subprocess.run(cmd, capture_output=True,
                          timeout=60 + 10 * (budget_ms // 1000), env=env)
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    return proc.returncode, out


def run(budget_ms: int = 0) -> List[Finding]:
    if budget_ms <= 0:
        budget_ms = int(os.environ.get("NATCHECK_FUZZ_MS", "2000"))
    findings: List[Finding] = []
    try:
        build()
    except subprocess.CalledProcessError as e:
        findings.append(Finding(
            "fuzz", "fuzz-build", "native/Makefile",
            "fuzz build failed: " +
            (e.stderr or b"").decode(errors="replace")[-800:]))
        return findings
    for name in TARGETS:
        try:
            rc, out = run_target(name, budget_ms)
        except subprocess.TimeoutExpired:
            findings.append(Finding(
                "fuzz", "fuzz-hang", f"native/fuzz/bin/fuzz_{name}",
                f"target wedged past its {budget_ms}ms budget"))
            continue
        bad = [ln for ln in out.splitlines()
               if any(mk in ln for mk in _BAD_MARKERS)]
        if rc != 0 or bad:
            head = "; ".join(bad[:3]) if bad else out.strip()[-400:]
            findings.append(Finding(
                "fuzz", "fuzz-crash", f"native/fuzz/bin/fuzz_{name}",
                f"fuzz run exited rc={rc}: {head}"))
    return findings
