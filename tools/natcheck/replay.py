"""replay lane — capture/replay round-trip gate (``tools/check.sh
--replay``).

The flight recorder's correctness contract, proven live in one process:

1. start a native server (builtin echo handler) and arm the dump tap
   with a fixed seed at 1-in-1 sampling;
2. drive a seeded run of tpu_std calls through the native client;
3. stop the capture, restart the server FRESH (new port, empty stats);
4. replay the capture through the native replay client
   (``nat_replay_run``) and require ZERO failed RPCs and
   response-count parity (ok == records captured == requests driven);
5. cross-check the capture files parse with the Python reader
   (``butil/recordio.py``) with byte-identical payloads — the
   native-written/Python-read half of the interop contract (the other
   half, Python-written/native-replayed, rides
   tests/test_rpc_dump_replay.py).

Each broken leg is a Finding; a clean run returns [].
"""
from __future__ import annotations

import glob
import os
import shutil
import tempfile
from typing import List

from tools.natcheck import Finding

N_CALLS = 40
SEED = 1234


def run() -> List[Finding]:
    where = "tools/check.sh --replay"
    try:
        from brpc_tpu import native

        if not native.available():
            return [Finding("replay", "no-native", where,
                            "native toolchain unavailable")]
    except Exception as e:
        return [Finding("replay", "no-native", where,
                        f"native import failed: {e}")]

    findings: List[Finding] = []
    capture_dir = tempfile.mkdtemp(prefix="natcheck_replay_")
    try:
        port = native.rpc_server_start(native_echo=True)
        rc = native.dump_start(capture_dir, every=1, seed=SEED)
        if rc != 0:
            native.rpc_server_stop()
            return [Finding("replay", "dump-start", where,
                            f"nat_dump_start rc={rc}")]
        sent = []
        h = native.channel_open("127.0.0.1", port)
        for i in range(N_CALLS):
            payload = (b"replay-lane-%04d-" % i) * (1 + i % 5)
            code, body, text = native.channel_call(
                h, "EchoService", "Echo", payload, timeout_ms=5000)
            if code != 0 or body != payload:
                findings.append(Finding(
                    "replay", "capture-drive", where,
                    f"seed call {i} failed: code={code} {text!r}"))
                break
            sent.append(payload)
        native.channel_close(h)
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if native.dump_status()["written"] >= len(sent):
                break
            time.sleep(0.05)
        native.dump_stop()
        native.rpc_server_stop()
        if findings:
            return findings

        st = native.dump_status()
        if st["written"] != len(sent) or st["drops"] != 0:
            findings.append(Finding(
                "replay", "capture-parity", where,
                f"captured {st['written']}/{len(sent)} records "
                f"(drops={st['drops']}) at 1-in-1 sampling"))

        # interop leg: the Python reader parses the native files with
        # byte-identical payloads, in capture order
        from brpc_tpu.butil.recordio import RecordReader

        got = []
        for path in sorted(glob.glob(os.path.join(capture_dir, "*.rio"))):
            with RecordReader(path) as reader:
                for meta, payload in reader:
                    got.append(payload)
                    if meta.get("service") != "EchoService":
                        findings.append(Finding(
                            "replay", "meta-drift", where,
                            f"record meta {meta!r} lost the service"))
        if got != sent:
            findings.append(Finding(
                "replay", "byte-identity", where,
                f"python reader saw {len(got)} payloads, "
                f"{sum(1 for a, b in zip(got, sent) if a != b)} of the "
                f"overlapping ones differ from what was sent"))

        # replay leg: fresh server, zero failures, count parity
        port2 = native.rpc_server_start(native_echo=True)
        try:
            res = native.replay_run("127.0.0.1", port2, capture_dir,
                                    times=1, concurrency=4,
                                    timeout_ms=5000)
        except (ValueError, ConnectionError) as e:
            native.rpc_server_stop()
            findings.append(Finding("replay", "replay-run", where, str(e)))
            return findings
        native.rpc_server_stop()
        if res["failed"] != 0 or res["ok"] != len(sent):
            findings.append(Finding(
                "replay", "replay-parity", where,
                f"replayed ok={res['ok']} failed={res['failed']} of "
                f"{len(sent)} captured requests — the contract is zero "
                f"failures and full response-count parity"))
    finally:
        shutil.rmtree(capture_dir, ignore_errors=True)
    return findings
