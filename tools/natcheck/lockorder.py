"""Lock-order verification — static acquires-while-holding analysis.

Every mutex in ``native/src/`` must carry a declared rank, either by
being a ``NatMutex<kLockRank...>`` (ranks defined in
``native/src/nat_lockrank.h``, validated at runtime under
``-DNAT_LOCKRANK=1``) or — for the few locks that must stay raw
(condition-variable partners, the robust shm fence) — by a
``// natcheck:rank(name, N)`` comment on or above the declaration.

The pass parses every TU, finds lock acquisition sites
(``lock_guard``/``unique_lock``/``scoped_lock``/``.lock()``/
``pthread_mutex_lock``), scopes each acquisition to its enclosing brace
block, and builds the acquires-while-holding graph, including one level
of interprocedural closure: a call made while holding L contributes
edges L -> every lock the callee (transitively) acquires, and a callee
that can hit a fiber-switch/blocking point makes the call site a
hold-across-switch finding.

Rules (suppress with ``// natcheck:allow(<rule>): why``):

- ``lock-undeclared``: a mutex declaration with no rank, or an
  acquisition of an expression that resolves to no declared lock.
- ``lock-order``: acquiring a lock whose rank is <= the rank of a lock
  already held (rank order is total, so monotonicity implies the
  acquisition graph is acyclic; a seeded cycle always has at least one
  edge that violates monotonicity and is reported here).
- ``lock-switch``: a fiber-switch point or blocking wait reached while
  holding a lock. Condition-variable waits are exempt for the lock the
  wait itself releases (``cv.wait(lk)``), but not for any OTHER held
  lock.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

if __package__ in (None, ""):  # `python tools/natcheck/lockorder.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

from tools.natcheck import Finding, REPO_ROOT  # noqa: E402

SRC_DIR = os.path.join(REPO_ROOT, "native", "src")
RANK_HEADER = "nat_lockrank.h"

_ALLOW = re.compile(r"natcheck:allow\(([a-z-]+)\)")
_RANK_COMMENT = re.compile(r"natcheck:rank\(\s*([\w.\-]+)\s*,\s*(\d+)\s*\)")
_RANK_CONST = re.compile(r"\b(kLockRank\w+)\s*=\s*(\d+)")

# declaration forms
_NATMUTEX_DECL = re.compile(
    r"\bNatMutex<\s*(kLockRank\w+|\d+)\s*>\s*(?:\*\s*)?(\w+)\s*"
    r"[;={\[(]")
_RAW_DECL = re.compile(
    r"\b(?:std::mutex|std::recursive_mutex|pthread_mutex_t)\s*"
    r"(?:\*\s*)?(\w+)\s*[;={\[]")

# acquisition forms (scrubbed text)
_GUARD = re.compile(
    r"\bstd::(lock_guard|unique_lock|scoped_lock)\s*(?:<[^>]*>)?\s+"
    r"(\w+)\s*[({]\s*([^;]*?)\s*[)}]\s*;")
_METHOD_LOCK = re.compile(r"([\w>.\[\]\*\-]+?)\s*(?:\.|->)\s*"
                          r"(lock|try_lock)\s*\(\s*\)")
_PTHREAD_LOCK = re.compile(
    r"\bpthread_mutex_(?:lock|trylock)\s*\(\s*([^)]+?)\s*\)")
_UNLOCK = re.compile(r"([\w>.\[\]\*\-]+?)\s*(?:\.|->)\s*unlock\s*\(\s*\)")

# fiber-switch / blocking-wait points (extends lint's switch-point
# knowledge: the scheduler's switch primitives, the shm futex wait, and
# plain sleeps). Condition-variable waits are handled separately so the
# lock the wait releases is exempt.
SWITCH_POINTS = {
    "yield", "butex_wait", "switch_out_to_main", "switch_into_fiber",
    "fctx_swap", "swapcontext", "futex_wait_shared", "sleep_for",
    "sleep_until", "usleep", "nanosleep", "epoll_wait", "join",
}
CV_WAITS = {"wait", "wait_for", "wait_until", "nat_cv_wait_for"}

# call-name stoplist: generic container/atomic method names that would
# otherwise collide with repo function summaries
_CALL_STOP = {
    "load", "store", "exchange", "fetch_add", "fetch_sub", "fetch_or",
    "fetch_and", "compare_exchange_weak", "compare_exchange_strong",
    "push_back", "pop_front", "pop_back", "emplace_back", "push",
    "front", "back", "size", "empty", "begin", "end", "clear", "find",
    "erase", "insert", "count", "reserve", "resize", "data", "c_str",
    "append", "substr", "get", "reset", "release", "lock", "unlock",
    "try_lock", "notify_one", "notify_all", "owns_lock", "str",
    "if", "for", "while", "switch", "return", "sizeof", "assert",
    "defined", "memcpy", "memset", "memcmp", "snprintf", "printf",
    "fprintf", "malloc", "free", "calloc", "min", "max", "move",
    "forward", "make_shared", "make_unique", "static_cast",
    "reinterpret_cast", "const_cast", "emplace",
}

_CALL = re.compile(r"\b([A-Za-z_]\w*)\s*\(")


def _strip_comments_and_strings(line: str) -> str:
    line = re.sub(r'"(?:[^"\\]|\\.)*"', '""', line)
    line = re.sub(r"'(?:[^'\\]|\\.)*'", "''", line)
    line = re.sub(r"/\*.*?\*/", "", line)
    line = re.sub(r"//.*", "", line)
    return line


class Lock:
    def __init__(self, var: str, name: str, rank: Optional[int],
                 where: str):
        self.var = var        # C++ variable / member name
        self.name = name      # declared lock name (rank table key)
        self.rank = rank      # None = undeclared
        self.where = where

    def __repr__(self):
        return f"Lock({self.name}, rank={self.rank})"


class Acq:
    """One acquisition: lock, offset range it is held over, guard var."""

    def __init__(self, lock: Lock, pos: int, end: int, line: int,
                 guard: Optional[str], expr: str, blocking: bool = True):
        self.lock = lock
        self.pos = pos
        self.end = end
        self.line = line
        self.guard = guard
        self.expr = expr
        # try_lock acquisitions cannot deadlock and are exempt from the
        # rank-monotonicity rule as the ACQUIRED side (they still rank-
        # constrain what is acquired while they are held)
        self.blocking = blocking


class FuncInfo:
    def __init__(self, name: str, path: str, start_line: int, body: str,
                 body_off: int):
        self.name = name
        self.path = path
        self.start_line = start_line
        self.body = body
        self.body_off = body_off
        self.acqs: List[Acq] = []
        self.calls: List[Tuple[str, int]] = []  # (callee, offset)
        self.direct_blocking: List[Tuple[str, int, List[str]]] = []
        # transitive summaries (filled by _propagate)
        self.trans_acquires: Set[str] = set()
        self.may_block = False
        self.block_via: str = ""


def parse_rank_table(src_dir: str) -> Dict[str, int]:
    """kLockRank* constants from nat_lockrank.h (if present)."""
    table: Dict[str, int] = {}
    p = os.path.join(src_dir, RANK_HEADER)
    if not os.path.exists(p):
        p = os.path.join(SRC_DIR, RANK_HEADER)
    if os.path.exists(p):
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            for m in _RANK_CONST.finditer(f.read()):
                table[m.group(1)] = int(m.group(2))
    return table


def _block_end(text: str, pos: int) -> int:
    """End offset of the innermost brace block containing `pos`
    (text is a function body starting at its opening '{')."""
    depth = 0
    opens: List[int] = []
    for k, ch in enumerate(text):
        if ch == "{":
            opens.append(k)
            depth += 1
        elif ch == "}":
            depth -= 1
            if opens:
                start = opens.pop()
                if start <= pos < k:
                    # first close whose open precedes pos and that
                    # brackets pos: since we pop innermost-first, the
                    # first such match IS the innermost block
                    return k
            if depth <= 0:
                return k
    return len(text)


def _last_ident(expr: str) -> Optional[str]:
    """`*g_resp_mu` -> g_resp_mu, `g_req_mu[i]` -> g_req_mu,
    `h->mu` -> mu, `w->fence` -> fence, `&w->fence` -> fence."""
    expr = expr.strip()
    # drop trailing index
    expr = re.sub(r"\[[^\]]*\]\s*$", "", expr).strip()
    m = re.search(r"([A-Za-z_]\w*)\s*$", expr)
    return m.group(1) if m else None


def collect_sources(src_dir: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for name in sorted(os.listdir(src_dir)):
        if name.endswith((".cpp", ".h", ".cc", ".hpp")):
            p = os.path.join(src_dir, name)
            with open(p, "r", encoding="utf-8", errors="replace") as f:
                out[p] = f.read()
    return out


def collect_locks(sources: Dict[str, str],
                  rank_table: Dict[str, int],
                  findings: List[Finding]) -> Dict[str, Lock]:
    """Map C++ variable name -> Lock. Duplicate variable names with
    different ranks are a finding (lock names must stay unique for the
    cross-TU graph to be meaningful)."""
    locks: Dict[str, Lock] = {}
    for path, text in sources.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = text.splitlines()
        stripped = [_strip_comments_and_strings(ln) for ln in lines]
        for i, ln in enumerate(stripped):
            for m in _NATMUTEX_DECL.finditer(ln):
                const, var = m.group(1), m.group(2)
                if const.isdigit():
                    rank: Optional[int] = int(const)
                    lname = var
                else:
                    rank = rank_table.get(const)
                    lname = const[len("kLockRank"):]
                    if rank is None:
                        findings.append(Finding(
                            "lockorder", "lock-undeclared",
                            f"{rel}:{i + 1}",
                            f"NatMutex rank constant {const} not found "
                            f"in {RANK_HEADER}"))
                _register(locks, var, lname, rank, f"{rel}:{i + 1}",
                          findings)
            for m in _RAW_DECL.finditer(ln):
                var = m.group(1)
                if ln.lstrip().startswith("extern"):
                    continue  # defined (and ranked) elsewhere
                rank_m = None
                for j in (i, i - 1):
                    if 0 <= j < len(lines):
                        rm = _RANK_COMMENT.search(lines[j])
                        if rm:
                            rank_m = rm
                            break
                if rank_m:
                    _register(locks, var, rank_m.group(1),
                              int(rank_m.group(2)), f"{rel}:{i + 1}",
                              findings)
                else:
                    if _allowed(lines, i, "lock-undeclared"):
                        continue
                    findings.append(Finding(
                        "lockorder", "lock-undeclared", f"{rel}:{i + 1}",
                        f"mutex `{var}` has no declared rank: make it a "
                        f"NatMutex<kLockRank...> or annotate "
                        f"`// natcheck:rank(name, N)`"))
                    _register(locks, var, var, None, f"{rel}:{i + 1}",
                              findings)
    return locks


def _register(locks: Dict[str, Lock], var: str, name: str,
              rank: Optional[int], where: str,
              findings: List[Finding]) -> None:
    prev = locks.get(var)
    if prev is not None and rank is not None and prev.rank is not None \
            and prev.rank != rank:
        findings.append(Finding(
            "lockorder", "lock-undeclared", where,
            f"lock variable `{var}` redeclared with a different rank "
            f"({prev.rank} at {prev.where} vs {rank}): lock variable "
            f"names must be unique across native/src"))
        return
    if prev is None or (prev.rank is None and rank is not None):
        locks[var] = Lock(var, name, rank, where)


def _allowed(lines: List[str], i: int, rule: str) -> bool:
    """allow() on the same line or anywhere in the contiguous comment
    block immediately above it (multi-line justifications are the norm
    for this rule set)."""
    if 0 <= i < len(lines):
        m = _ALLOW.search(lines[i])
        if m and m.group(1) == rule:
            return True
    j = i - 1
    while j >= 0 and i - j <= 8:
        stripped = lines[j].strip()
        if not stripped.startswith("//") and not stripped.startswith("#"):
            break
        m = _ALLOW.search(lines[j])
        if m and m.group(1) == rule:
            return True
        j -= 1
    return False


_SIG = re.compile(
    r"(?:^|[;}\n])\s*(?:[\w:<>,&*~\s]+?\s)?"
    r"([A-Za-z_~]\w*(?:::[A-Za-z_~]\w*)*)\s*\(")
_KEYWORDS = {"if", "for", "while", "switch", "catch", "return",
             "sizeof", "else", "do", "new", "delete", "defined"}


_LAMBDA = re.compile(r"\[[&=]?(?:this|[&=\w,\s]*)\]\s*(?:\([^)]*\)\s*)?"
                     r"(?:mutable\s*)?(?:->\s*[\w:<>]+\s*)?\{")


def _extract_lambdas(body: str) -> Tuple[str, List[Tuple[int, str]]]:
    """Blank out lambda literals from `body` (a thread/hook body runs
    CONCURRENTLY or later — it must not contribute acquisitions or
    blocking calls to the enclosing function's summary) and return them
    as (offset, text) so they can be checked as anonymous functions."""
    out = []
    while True:
        m = _LAMBDA.search(body)
        if not m:
            break
        start = m.end() - 1
        depth = 0
        k = start
        while k < len(body):
            if body[k] == "{":
                depth += 1
            elif body[k] == "}":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        out.append((m.start(), body[start:k + 1]))
        blank = "".join(c if c == "\n" else " "
                        for c in body[m.start():k + 1])
        body = body[:m.start()] + blank + body[k + 1:]
    return body, out


def parse_functions(path: str, text: str) -> List[FuncInfo]:
    """Function definitions: name + brace-matched body. Crude but
    effective for this tree's style (same discipline as lint.py)."""
    scrubbed = "\n".join(_strip_comments_and_strings(ln)
                         for ln in text.splitlines())
    out: List[FuncInfo] = []
    i = 0
    while i < len(scrubbed):
        m = _SIG.search(scrubbed, i)
        if not m:
            break
        name = m.group(1).split("::")[-1]
        if name in _KEYWORDS:
            i = m.end()
            continue
        # match the parameter parens
        depth = 0
        k = m.end() - 1
        while k < len(scrubbed):
            if scrubbed[k] == "(":
                depth += 1
            elif scrubbed[k] == ")":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        if k >= len(scrubbed):
            break
        tail = scrubbed[k + 1:k + 80]
        tm = re.match(r"\s*(?:const)?\s*(?:noexcept)?\s*"
                      r"(?:->\s*[\w:<>]+\s*)?\{", tail)
        if not tm:
            i = m.end()
            continue
        body_start = k + 1 + tm.end() - 1  # offset of '{'
        # brace-match the body
        depth = 0
        j = body_start
        while j < len(scrubbed):
            if scrubbed[j] == "{":
                depth += 1
            elif scrubbed[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        body = scrubbed[body_start:j + 1]
        body, lambdas = _extract_lambdas(body)
        start_line = scrubbed.count("\n", 0, body_start) + 1
        out.append(FuncInfo(name, path, start_line, body, body_start))
        for off, ltext in lambdas:
            out.append(FuncInfo(
                f"{name}<lambda>", path,
                start_line + body[:off].count("\n"), ltext,
                body_start + off))
        i = j + 1 if j > i else m.end()
    return out


def analyze_function(fn: FuncInfo, locks: Dict[str, Lock],
                     findings: List[Finding], rel: str,
                     lines: List[str]) -> None:
    body = fn.body

    def lineno(off: int) -> int:
        return fn.start_line + body[:off].count("\n")

    guards: Dict[str, Acq] = {}
    # guard-style acquisitions
    for m in _GUARD.finditer(body):
        kind, gvar, args = m.group(1), m.group(2), m.group(3)
        if "defer_lock" in args or "adopt_lock" in args:
            continue
        first = args.split(",")[0]
        ident = _last_ident(first)
        if ident is None:
            continue
        lk = locks.get(ident)
        ln = lineno(m.start())
        if lk is None:
            if not _allowed(lines, ln - 1, "lock-undeclared"):
                findings.append(Finding(
                    "lockorder", "lock-undeclared", f"{rel}:{ln}",
                    f"acquisition of `{first.strip()}` resolves to no "
                    f"declared lock"))
            continue
        # the guard holds to the end of its block, or to an explicit
        # guard.unlock() (the tree unlocks deliberately before calling
        # set_failed and friends — that discipline must be visible here)
        end = _block_end(body, m.start())
        um = re.search(r"\b%s\s*\.\s*unlock\s*\(" % re.escape(gvar),
                       body[m.end():end])
        if um:
            end = m.end() + um.start()
        acq = Acq(lk, m.start(), end, ln, gvar, first.strip(),
                  blocking="try_to_lock" not in args)
        fn.acqs.append(acq)
        guards[gvar] = acq
    # manual .lock() / pthread_mutex_lock
    for m in list(_METHOD_LOCK.finditer(body)):
        obj = m.group(1)
        ident = _last_ident(obj)
        if ident is None or ident in guards:
            # guard.lock()/unlock() on a unique_lock var: treat the
            # guard's own range as authoritative (re-lock windows are
            # rare and the coarse range is the conservative direction)
            continue
        lk = locks.get(ident)
        if lk is None:
            continue  # `.lock()` on a non-mutex (unique_lock var etc.)
        end = len(body)
        um = re.search(re.escape(obj) + r"\s*(?:\.|->)\s*unlock\s*\(",
                       body[m.end():])
        if um:
            end = m.end() + um.start()
        fn.acqs.append(Acq(lk, m.start(), end, lineno(m.start()), None,
                           obj, blocking=m.group(2) == "lock"))
    for m in _PTHREAD_LOCK.finditer(body):
        ident = _last_ident(m.group(1))
        lk = locks.get(ident) if ident else None
        if lk is None:
            continue
        end = len(body)
        um = re.search(r"pthread_mutex_unlock\s*\(\s*" +
                       re.escape(m.group(1).strip()), body[m.end():])
        if um:
            end = m.end() + um.start()
        fn.acqs.append(Acq(
            lk, m.start(), end, lineno(m.start()), None,
            m.group(1).strip(),
            blocking="trylock" not in body[m.start():m.start() + 24]))

    # call sites + direct switch points
    for m in _CALL.finditer(body):
        name = m.group(1)
        if name in _CALL_STOP:
            continue
        # `::shutdown(fd, ...)` / `::close(fd)` are libc syscalls, not
        # the repo methods that share their names
        if body[max(0, m.start() - 2):m.start()] == "::" and (
                m.start() < 3 or not (body[m.start() - 3].isalnum() or
                                      body[m.start() - 3] == "_")):
            continue
        args_end = body.find(")", m.end())
        args = body[m.end():args_end] if args_end > 0 else ""
        if name in SWITCH_POINTS:
            fn.direct_blocking.append((name, m.start(), []))
        elif name in CV_WAITS:
            # the lock(s) this wait releases are exempt: collect guard
            # vars named in the args
            exempt = [g for g in guards if re.search(
                r"\b%s\b" % re.escape(g), args)]
            fn.direct_blocking.append((name, m.start(), exempt))
        else:
            fn.calls.append((name, m.start()))


def _propagate(funcs: Dict[str, List[FuncInfo]]) -> None:
    """Fixpoint transitive closure of acquires + may-block over the
    by-name call graph."""
    for fns in funcs.values():
        for fn in fns:
            fn.trans_acquires = {a.lock.name for a in fn.acqs
                                 if a.blocking}
            fn.may_block = bool(fn.direct_blocking)
            if fn.direct_blocking:
                fn.block_via = fn.direct_blocking[0][0]
    changed = True
    while changed:
        changed = False
        for fns in funcs.values():
            for fn in fns:
                for callee, _ in fn.calls:
                    for cf in funcs.get(callee, []):
                        extra = cf.trans_acquires - fn.trans_acquires
                        if extra:
                            fn.trans_acquires |= extra
                            changed = True
                        if cf.may_block and not fn.may_block:
                            fn.may_block = True
                            fn.block_via = f"{callee} -> {cf.block_via}"
                            changed = True


def check(src_dir: str = SRC_DIR,
          dump: bool = False) -> List[Finding]:
    findings: List[Finding] = []
    sources = collect_sources(src_dir)
    rank_table = parse_rank_table(src_dir)
    locks = collect_locks(sources, rank_table, findings)

    funcs: Dict[str, List[FuncInfo]] = {}
    per_file: Dict[str, List[FuncInfo]] = {}
    file_lines: Dict[str, List[str]] = {}
    for path, text in sources.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = text.splitlines()
        file_lines[path] = lines
        flist = parse_functions(path, text)
        per_file[path] = flist
        for fn in flist:
            analyze_function(fn, locks, findings, rel, lines)
            funcs.setdefault(fn.name, []).append(fn)
    _propagate(funcs)

    edges: List[Tuple[str, str, str, str]] = []  # (held, acquired, where, via)
    for path, flist in per_file.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = file_lines[path]
        for fn in flist:
            body = fn.body

            def lineno(off: int) -> int:
                return fn.start_line + body[:off].count("\n")

            for acq in fn.acqs:
                held = acq.lock
                # direct nested acquisitions
                for other in fn.acqs:
                    if other is acq or not other.blocking:
                        continue
                    if acq.pos < other.pos < acq.end:
                        edges.append((held.name, other.lock.name,
                                      f"{rel}:{other.line}", "direct"))
                        _check_edge(held, other.lock,
                                    f"{rel}:{other.line}", "direct",
                                    lines, other.line, findings)
                # calls while held
                for callee, off in fn.calls:
                    if not (acq.pos < off < acq.end):
                        continue
                    for cf in funcs.get(callee, []):
                        ln = lineno(off)
                        for lname in sorted(cf.trans_acquires):
                            tgt = _lock_by_name(locks, lname)
                            if tgt is None:
                                continue
                            edges.append((held.name, lname,
                                          f"{rel}:{ln}",
                                          f"via {callee}()"))
                            _check_edge(held, tgt, f"{rel}:{ln}",
                                        f"via {callee}()", lines, ln,
                                        findings)
                        if cf.may_block:
                            ln = lineno(off)
                            if not _allowed(lines, ln - 1,
                                            "lock-switch"):
                                findings.append(Finding(
                                    "lockorder", "lock-switch",
                                    f"{rel}:{ln}",
                                    f"`{held.name}` (rank "
                                    f"{held.rank}) held across a "
                                    f"blocking/switch point: "
                                    f"{callee} -> {cf.block_via}"))
                        break  # one summary per callee name is enough
                # direct switch points while held
                for bname, off, exempt in fn.direct_blocking:
                    if not (acq.pos < off < acq.end):
                        continue
                    if acq.guard is not None and acq.guard in exempt:
                        continue  # cv wait releases THIS lock
                    ln = lineno(off)
                    if _allowed(lines, ln - 1, "lock-switch"):
                        continue
                    findings.append(Finding(
                        "lockorder", "lock-switch", f"{rel}:{ln}",
                        f"`{held.name}` (rank {held.rank}) held across "
                        f"fiber-switch/blocking point `{bname}()`"))

    if dump:
        seen = set()
        print("== lock rank table ==")
        for var, lk in sorted(locks.items(),
                              key=lambda kv: (kv[1].rank is None,
                                              kv[1].rank or 0)):
            print(f"  {lk.rank if lk.rank is not None else '??':>4} "
                  f" {lk.name:<24} ({var}, {lk.where})")
        print("== acquires-while-holding edges ==")
        for held, acquired, where, via in edges:
            key = (held, acquired, via.split(" ")[0])
            if key in seen:
                continue
            seen.add(key)
            print(f"  {held} -> {acquired}  [{via}] at {where}")
    return _dedupe(findings)


def _lock_by_name(locks: Dict[str, Lock], name: str) -> Optional[Lock]:
    for lk in locks.values():
        if lk.name == name:
            return lk
    return None


def _check_edge(held: Lock, acquired: Lock, where: str, via: str,
                lines: List[str], line: int,
                findings: List[Finding]) -> None:
    if held.rank is None or acquired.rank is None:
        return  # undeclared is its own finding
    if held.name == acquired.name or acquired.rank <= held.rank:
        if _allowed(lines, line - 1, "lock-order"):
            return
        findings.append(Finding(
            "lockorder", "lock-order", where,
            f"acquires `{acquired.name}` (rank {acquired.rank}) while "
            f"holding `{held.name}` (rank {held.rank}) [{via}] — rank "
            f"must strictly increase on nested acquisition"))


def _dedupe(findings: List[Finding]) -> List[Finding]:
    seen = set()
    out = []
    for f in findings:
        key = (f.rule, f.where, f.message)
        if key in seen:
            continue
        seen.add(key)
        out.append(f)
    return out


def run(src_dir: str = SRC_DIR) -> List[Finding]:
    return check(src_dir)


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    src = SRC_DIR
    dump = "--dump" in sys.argv
    for a in sys.argv[1:]:
        if a != "--dump":
            src = a
    fs = check(src, dump=dump)
    for f in fs:
        print(f)
    sys.exit(1 if fs else 0)
