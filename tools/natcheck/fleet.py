"""natcheck fleet round (tools/check.sh --fleet): the fleet observatory
driven against a LIVE 3-server group.

Three native echo server subprocesses behind a file naming feed, real
traffic through real channels, then the whole ISSUE-16 chain end to
end: wire-native builtin.stats scrape of every member -> histogram
merge -> fleet quantiles -> SLO engine. The merge contract is checked
EXACTLY: the merged method buckets must equal the bucket-wise sum of
every member's buckets (log2 histograms admit an exact merge — that is
the reason raw buckets ride the wire instead of percentiles), and the
fleet quantile must come from those merged buckets.
"""
from __future__ import annotations

import os
import signal
import tempfile
import time
from typing import List

from tools.natcheck import Finding, REPO_ROOT

WHERE = "tools/check.sh --fleet"
SERVERS = 3
CALLS_PER_BACKEND = 200


def _finding(rule: str, msg: str) -> Finding:
    return Finding("fleet", rule, WHERE, msg)


def run() -> List[Finding]:
    findings: List[Finding] = []
    import sys

    sys.path.insert(0, REPO_ROOT)
    from brpc_tpu import native  # noqa: F401 — fail early when .so missing
    from brpc_tpu.bench import _spawn_swarm_server
    from brpc_tpu.fleet import FleetObservatory, SloObjective, hist

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = []
    ports = []
    nf_path = None
    obs = None
    try:
        base_candidates = [22100, 24100, 26100, 28100, 20100, 18100]
        ci = 0
        while len(procs) < SERVERS and ci < len(base_candidates):
            base = base_candidates[ci]
            ci += 1
            proc = _spawn_swarm_server(base, 1, REPO_ROOT, env)
            if proc is not None:
                procs.append(proc)
                ports.append(base)
        if len(procs) < SERVERS:
            return [_finding("no-ports",
                             "could not bind a 3-server group (all "
                             "candidate port ranges taken)")]

        nf = tempfile.NamedTemporaryFile("w", suffix=".fleet.ns",
                                         delete=False)
        nf_path = nf.name
        for p in ports:
            nf.write(f"127.0.0.1:{p}\n")
        nf.close()

        # real traffic through real channels, per member
        from brpc_tpu import native as nat

        for p in ports:
            ch = nat.channel_open("127.0.0.1", p)
            if not ch:
                findings.append(_finding(
                    "dial", f"could not dial live member 127.0.0.1:{p}"))
                continue
            try:
                failed = 0
                for _ in range(CALLS_PER_BACKEND):
                    rc, _resp, _err = nat.channel_call(
                        ch, "EchoService", "Echo", b"fleet-round",
                        timeout_ms=5000)
                    failed += rc != 0
                if failed:
                    findings.append(_finding(
                        "traffic",
                        f"{failed}/{CALLS_PER_BACKEND} echo calls "
                        f"failed against 127.0.0.1:{p}"))
            finally:
                nat.channel_close(ch)
        if findings:
            return findings

        obs = FleetObservatory(
            naming_url=f"file://{nf_path}",
            interval_s=0.5,
            objectives=[SloObjective(name="fleet-round-p99",
                                     kind="latency", lane="echo",
                                     method="EchoService.Echo",
                                     ceiling_ms=1000.0, budget=0.001,
                                     fast_window_s=5, slow_window_s=10)],
            register_bvars=False)
        deadline = time.time() + 10
        merged = obs.scrape_once()
        while (len(merged.get("backends", {})) < SERVERS
               and time.time() < deadline):
            time.sleep(0.3)
            merged = obs.scrape_once()

        backends = merged.get("backends", {})
        up = [ep for ep, b in backends.items() if b.get("up")]
        if len(up) != SERVERS:
            findings.append(_finding(
                "membership",
                f"expected {SERVERS} live members, scraped "
                f"{len(up)} up of {len(backends)} known"))

        row = merged.get("methods", {}).get("echo/EchoService.Echo")
        if row is None:
            findings.append(_finding(
                "merge", "merged rollup has no echo/EchoService.Echo "
                         "row after real traffic"))
            return findings
        want = SERVERS * CALLS_PER_BACKEND
        if row["count"] < want:
            findings.append(_finding(
                "merge",
                f"merged count {row['count']} < {want} sent calls — "
                f"a member's stream was dropped from the merge"))

        # the EXACT-merge contract: merged buckets == bucket-wise sum of
        # every member's raw buckets off the wire
        summed = [0] * hist.NBUCKETS
        for snap in obs.snapshots().values():
            if not (snap.ok and snap.data):
                continue
            for m in snap.data.get("methods", []):
                if (m["lane"], m["method"]) == ("echo",
                                                "EchoService.Echo"):
                    summed = hist.merge(summed,
                                        hist.dense(m.get("buckets", [])))
        if summed != row["buckets"]:
            findings.append(_finding(
                "merge-exact",
                "merged histogram != bucket-wise sum of member "
                "histograms — the exact-merge contract is broken"))
        p99 = hist.quantile(row["buckets"], 0.99)
        if not 0.0 < p99 < 60e9:
            findings.append(_finding(
                "quantile",
                f"fleet p99 {p99}ns from merged buckets is not sane"))

        # the SLO engine saw the streams and stands quiet (1s ceiling on
        # a loopback echo cannot burn)
        st = obs.slo.status().get("fleet-round-p99")
        if st is None or st["stream_total"] <= 0:
            findings.append(_finding(
                "slo", "SLO engine did not ingest the merged stream"))
        elif st["alert"]:
            findings.append(_finding(
                "slo", "SLO alert firing on an unburned objective "
                       "(1s ceiling on loopback echo)"))
    finally:
        if obs is not None:
            obs.close()
        for proc in procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except Exception:
                proc.kill()
                proc.wait(timeout=10)
        if nf_path is not None:
            try:
                os.unlink(nf_path)
            except OSError:
                pass
    return findings
