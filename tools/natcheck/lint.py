"""Concurrency lint — repo invariants over native/src/, regex/clang-agnostic.

Rules (suppress a line with ``// natcheck:allow(<rule>): why`` on the same
or the preceding line — the why is mandatory review surface, like a
sanitizer suppressions entry):

- ``atomic-order``: every std::atomic load/store/RMW must name an explicit
  std::memory_order. Implicit seq_cst hides the author's intent and makes
  the cheap-on-x86/expensive-on-ARM distinction invisible in review (the
  single-writer stat cells and Dekker parking-lot patterns here depend on
  exactly which order each access uses).

- ``static-dtor``: in any file that spawns threads which can outlive
  ``exit()`` (the runtime's workers/dispatchers/drainers are never joined
  at process exit), no function-local or namespace-scope ``static`` object
  of a nontrivially-destructible type. __cxa_atexit destroys such statics
  while detached threads still use them — the PR-1 bench-exit SIGSEGV
  class. Leak intentionally instead: ``static T* x = new T;``.

- ``seqlock-recheck``: a reader that loads a seqlock sequence counter and
  then copies the protected payload must re-load the counter to validate
  the copy (torn reads are the whole point of the pattern).

- ``fault-gate``: outside nat_fault.{h,cpp}, fault hooks must go through
  the ``NAT_FAULT_POINT`` macro — a direct ``nat_fault_hit()`` call
  skips the one-predictable-branch gate and puts a function call (plus a
  per-site op-counter RMW) on the disabled hot path.

- ``resacct``: in a TU that uses the nat_res accounting macros (an
  "accounted subsystem" of the memory observatory, ISSUE 14), every raw
  allocation — ``new`` / ``malloc`` / ``calloc`` / ``realloc`` /
  ``mmap`` — must sit within three lines of a ``NAT_RES_ALLOC`` /
  ``NAT_RES_STATIC`` call, be a declared deliberate leak
  (``natcheck:leak``), or carry a ``natcheck:allow(resacct): why``
  escape. An unaccounted allocation in an accounted subsystem is
  invisible to /heap/native, the nat_mem_* ledger and the RSS
  reconciliation — exactly the drift this pass exists to stop.

- ``sigsafe``: a function named ``*_sighandler`` (and every in-file
  function it reaches) is a signal handler body and must stay
  async-signal-safe: no allocation (malloc/new/std:: containers), no
  locks, no stdio, no symbolization. Raw syscalls, lock-free atomics and
  mem* are the legal vocabulary (nat_prof's SIGPROF sampler is the
  motivating case — a malloc in a signal handler deadlocks against the
  interrupted allocator).
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Tuple

from tools.natcheck import Finding, REPO_ROOT

SRC_DIR = os.path.join(REPO_ROOT, "native", "src")

_ALLOW = re.compile(r"natcheck:allow\(([a-z-]+)\)")
# A declared deliberate leak (the refown pass's leak registry — one
# source of truth shared with native/lsan.supp) also satisfies the
# static-dtor rule: a leaked object is never destroyed at exit.
_LEAK_DECL = re.compile(r"natcheck:leak\(([\w:.\-]+)\)")

_ATOMIC_METHODS = (
    r"load|store|exchange|fetch_add|fetch_sub|fetch_and|fetch_or|"
    r"fetch_xor|compare_exchange_weak|compare_exchange_strong")
_ATOMIC_CALL = re.compile(r"(?:\.|->)\s*(%s)\s*\(" % _ATOMIC_METHODS)

# thread-spawning file: constructs a std::thread (joined-at-stop or not,
# the process can always exit() while it runs) or detaches one.
_SPAWNS_THREAD = re.compile(
    r"new\s+std::thread|std::thread\s*\(|\.detach\s*\(\s*\)")

_STD_NONTRIVIAL = (
    r"string|vector|deque|list|map|unordered_map|set|unordered_set|queue|"
    r"function|shared_ptr|unique_ptr|thread|condition_variable|"
    r"condition_variable_any|f?stream|ofstream|ifstream|stringstream")

# `static [const] TYPE name ...` where TYPE is a nontrivial std:: type by
# value (no * / & between type and name). thread_local statics are a
# different lifetime (thread exit, not process exit) and are not this rule.
_STATIC_STD = re.compile(
    r"\bstatic\s+(?:const\s+)?(std::(?:%s)\b(?:<[^;()]*>)?)\s*(?![\w:<])"
    r"[^;*&()=]*\s+\w+\s*([;({=\[])" % _STD_NONTRIVIAL)
_STATIC_ANY = re.compile(
    r"\bstatic\s+(?:const\s+)?([A-Z]\w*)(?:<[^;()]*>)?\s+\w+\s*([;({=\[])")
_THREAD_LOCAL = re.compile(r"\bthread_local\b")

_SEQ_LOAD = re.compile(r"([A-Za-z_]\w*)\s*(?:\.|->)\s*seq\s*(?:\.|->)\s*"
                       r"load\s*\(")

# async-signal-UNSAFE vocabulary for *_sighandler bodies: allocation,
# locks, stdio/formatting, C++ container types, symbolization
_SIGSAFE_FORBID = re.compile(
    r"\bmalloc\s*\(|\bcalloc\s*\(|\brealloc\s*\(|\bfree\s*\(|"
    r"\bnew\s+\w|\bdelete\s+|\bs?n?printf\s*\(|\bfprintf\s*\(|"
    r"std::(?:string|vector|map|unordered_map|deque|set|function)\b|"
    r"lock_guard|unique_lock|(?:\.|->)\s*lock\s*\(|\bpthread_mutex|"
    r"\bmutex\b|\bdladdr\s*\(|__cxa_demangle|\bfopen\s*\(|\bthrow\b")


_RES_MACRO = re.compile(r"\bNAT_RES_(?:ALLOC|FREE|STATIC)\s*\(")
# raw allocation vocabulary the resacct rule pairs with the ledger:
# object news (incl. array news), the malloc family, and mmap
_RAW_ALLOC = re.compile(
    r"\bnew\s+[A-Za-z_][\w:<>,\s*&]*?[({\[;]|"
    r"\bmalloc\s*\(|\bcalloc\s*\(|\brealloc\s*\(|\bmmap\s*\(")


def _leak_declared(lines, i: int) -> bool:
    """natcheck:leak(sym) on the statement or its contiguous leading
    comment block (the static-dtor rule's escape, shared by resacct: a
    declared deliberate leak is reviewed surface). The `new` of a
    leaked global often sits on a CONTINUATION line
    (``Type&\\n    x = *new Type()``), so walk back to the statement
    start first."""
    if not (0 <= i < len(lines)):
        return False
    # hop to the start of the (possibly multi-line) statement
    j = i
    while j > 0 and i - j < 4:
        prev = lines[j - 1].strip()
        if prev == "" or prev.startswith("//") or prev.startswith("#") \
                or prev.endswith((";", "{", "}")):
            break
        j -= 1
    for k in range(j, i + 1):
        if _LEAK_DECL.search(lines[k]):
            return True
    k = j - 1
    while k >= 0 and j - k <= 8:
        stripped = lines[k].strip()
        if not stripped.startswith("//") and not stripped.startswith("#"):
            break
        if _LEAK_DECL.search(lines[k]):
            return True
        k -= 1
    return False


def _strip_comments_and_strings(line: str) -> str:
    """Good-enough single-line scrub so tokens in comments/strings don't
    trip rules (block comments spanning lines are rare in this tree and
    the suppression mechanism covers stragglers)."""
    line = re.sub(r'"(?:[^"\\]|\\.)*"', '""', line)
    line = re.sub(r"'(?:[^'\\]|\\.)*'", "''", line)
    line = re.sub(r"/\*.*?\*/", "", line)  # single-line block comments
    line = re.sub(r"//.*", "", line)
    return line


def _allowed(lines: List[str], i: int, rule: str) -> bool:
    for j in (i, i - 1):
        if 0 <= j < len(lines):
            m = _ALLOW.search(lines[j])
            if m and m.group(1) == rule:
                return True
    if rule == "static-dtor":
        # natcheck:leak(sym) on the declaration line or its CONTIGUOUS
        # comment block is the declared-leak registry's escape for this
        # rule (an unrelated declaration past intervening code is not)
        if 0 <= i < len(lines) and _LEAK_DECL.search(lines[i]):
            return True
        j = i - 1
        while j >= 0 and i - j <= 8:
            stripped = lines[j].strip()
            if not stripped.startswith("//") and \
                    not stripped.startswith("#"):
                break
            if _LEAK_DECL.search(lines[j]):
                return True
            j -= 1
    return False


def _balanced_args(text: str, open_idx: int) -> str:
    """Text inside the paren group opening at open_idx (best effort)."""
    depth = 0
    for k in range(open_idx, len(text)):
        if text[k] == "(":
            depth += 1
        elif text[k] == ")":
            depth -= 1
            if depth == 0:
                return text[open_idx + 1:k]
    return text[open_idx + 1:]


def _class_bodies(sources: Dict[str, str]) -> Dict[str, str]:
    """Map class/struct name -> body text, across all sources (crude brace
    matcher; nested classes fold into the parent, which is fine here)."""
    bodies: Dict[str, str] = {}
    decl = re.compile(r"\b(?:class|struct)\s+(\w+)\s*(?:final\s*)?"
                      r"(?::[^{;]*)?\{")
    for text in sources.values():
        for m in decl.finditer(text):
            depth = 0
            for k in range(m.end() - 1, len(text)):
                if text[k] == "{":
                    depth += 1
                elif text[k] == "}":
                    depth -= 1
                    if depth == 0:
                        bodies.setdefault(m.group(1),
                                          text[m.end():k])
                        break
    return bodies


def _nontrivial_classes(sources: Dict[str, str]) -> set:
    """Repo-defined types whose static-storage destruction at exit is a
    hazard: user-declared dtor, or a nontrivially-destructible member
    held BY VALUE (a pointer/reference member, function parameter, or
    return type mentioning the type does not make the holder's destructor
    nontrivial)."""
    # by-value member declaration: type, whitespace, identifier, then a
    # declarator terminator — `std::vector<int>* p;` (no whitespace after
    # the type) and `void f(std::vector<int> v)` (')' terminator) don't
    # match.
    member = re.compile(r"\bstd::(?:%s)\b(?:<[^;()]*>)?\s+\w+\s*[;={\[]"
                        % _STD_NONTRIVIAL)
    out = set()
    bodies = _class_bodies(sources)
    for name, body in bodies.items():
        if re.search(r"~\s*%s\s*\(" % re.escape(name), body) or \
                member.search(body):
            out.add(name)
    # transitive closure: a class holding a nontrivial class by value
    changed = True
    while changed:
        changed = False
        for name, body in bodies.items():
            if name in out:
                continue
            if any(re.search(r"\b%s\s+\w+\s*[;={\[]" % re.escape(c), body)
                   for c in out):
                out.add(name)
                changed = True
    return out


def _function_blocks(text: str) -> List[Tuple[int, str]]:
    """(start_lineno, body) for each top-level brace block following a
    ')' — i.e. function definitions (crude but effective for this tree)."""
    blocks = []
    sig = re.compile(r"\)\s*(?:const\s*)?(?:noexcept\s*)?\{")
    depth = 0
    i = 0
    while i < len(text):
        m = sig.search(text, i)
        if not m:
            break
        start = m.end() - 1
        depth = 0
        for k in range(start, len(text)):
            if text[k] == "{":
                depth += 1
            elif text[k] == "}":
                depth -= 1
                if depth == 0:
                    blocks.append((text.count("\n", 0, start) + 1,
                                   text[start:k]))
                    i = k
                    break
        else:
            break
        i = max(i, m.end())
    return blocks


# control-flow keywords also match `name (...) {` — they are not
# function definitions, and treating them as callees would attribute the
# file's lexically-first if/while block to signal context
_CPP_KEYWORDS = {"if", "for", "while", "switch", "catch", "return",
                 "sizeof", "alignof", "decltype", "else", "do", "new",
                 "delete", "throw", "static_assert"}


def _named_function_bodies(scrubbed: str) -> Dict[str, Tuple[int, str]]:
    """name -> (start_lineno, body) for function DEFINITIONS (a paren
    group whose close is followed by an opening brace; crude but right
    for this tree — declarations end in ';' and are skipped)."""
    out: Dict[str, Tuple[int, str]] = {}
    for m in re.finditer(r"\b(\w+)\s*\(", scrubbed):
        if m.group(1) in _CPP_KEYWORDS:
            continue
        open_idx = m.end() - 1
        depth = 0
        close = -1
        for k in range(open_idx, min(open_idx + 4000, len(scrubbed))):
            if scrubbed[k] == "(":
                depth += 1
            elif scrubbed[k] == ")":
                depth -= 1
                if depth == 0:
                    close = k
                    break
        if close < 0:
            continue
        tail = scrubbed[close + 1:close + 48].lstrip()
        if not re.match(r"(?:const\s*)?(?:noexcept\s*)?\{", tail):
            continue
        body_open = scrubbed.index("{", close)
        depth = 0
        for k in range(body_open, len(scrubbed)):
            if scrubbed[k] == "{":
                depth += 1
            elif scrubbed[k] == "}":
                depth -= 1
                if depth == 0:
                    out.setdefault(
                        m.group(1),
                        (scrubbed.count("\n", 0, body_open) + 1,
                         scrubbed[body_open:k]))
                    break
        else:
            continue
    return out


def lint_file(path: str, text: str, nontrivial: set) -> List[Finding]:
    findings: List[Finding] = []
    rel = os.path.relpath(path, REPO_ROOT)
    lines = text.splitlines()
    stripped = [_strip_comments_and_strings(ln) for ln in lines]

    # ---- atomic-order -----------------------------------------------------
    # scan the joined scrubbed text: argument lists often span lines
    scrubbed = "\n".join(stripped)
    for m in _ATOMIC_CALL.finditer(scrubbed):
        args = _balanced_args(scrubbed, m.end() - 1)
        if "memory_order" in args:
            continue
        i = scrubbed.count("\n", 0, m.start())
        # `.load()`-style calls on non-atomics (IOBuf etc.) don't use
        # these method names in this tree; exceptions use allow().
        if _allowed(lines, i, "atomic-order"):
            continue
        findings.append(Finding(
            "lint", "atomic-order", f"{rel}:{i + 1}",
            f"atomic {m.group(1)}() without an explicit "
            f"std::memory_order"))

    # ---- static-dtor ------------------------------------------------------
    def _is_function_def(m) -> bool:
        # `static std::string helper(args...) {` is a function returning
        # the type, not a static object: a paren group whose close is
        # followed by `{` (or by `;` with a parameter-list-shaped inside,
        # i.e. a forward declaration) is not a variable.
        if m.group(2) != "(":
            return False
        open_idx = m.end() - 1
        depth, k = 0, open_idx
        for k in range(open_idx, min(open_idx + 4000, len(scrubbed))):
            if scrubbed[k] == "(":
                depth += 1
            elif scrubbed[k] == ")":
                depth -= 1
                if depth == 0:
                    break
        tail = scrubbed[k + 1:k + 40].lstrip()
        inside = scrubbed[open_idx + 1:k]
        if tail.startswith("{"):
            return True
        # parameter-list shapes: `const X&`, `int a, int b`, `void`
        if tail.startswith(";") and re.search(
                r"(\bconst\b|&|\*|\w+\s+\w+|^\s*void\s*$)", inside):
            return True
        return False

    if _SPAWNS_THREAD.search(text):
        for m in list(_STATIC_STD.finditer(scrubbed)) + \
                list(_STATIC_ANY.finditer(scrubbed)):
            hit = m.group(1)
            if not hit.startswith("std::") and hit not in nontrivial:
                continue
            i = scrubbed.count("\n", 0, m.start())
            if _THREAD_LOCAL.search(stripped[i]):
                continue
            if _is_function_def(m):
                continue
            if _allowed(lines, i, "static-dtor"):
                continue
            findings.append(Finding(
                "lint", "static-dtor", f"{rel}:{i + 1}",
                f"static {hit} has a nontrivial destructor in a "
                f"thread-spawning file — __cxa_atexit destroys it while "
                f"detached threads may still use it (PR-1 bench-exit "
                f"SIGSEGV class); leak it instead: static T* x = new T;"))

    # ---- fault-gate -------------------------------------------------------
    # nat_fault.h holds the macro definition and nat_fault.cpp the
    # implementation; everywhere else the gate macro is the only legal
    # way to reach the fault table.
    if os.path.basename(path) not in ("nat_fault.h", "nat_fault.cpp"):
        for m in re.finditer(r"\bnat_fault_hit\s*\(", scrubbed):
            i = scrubbed.count("\n", 0, m.start())
            if _allowed(lines, i, "fault-gate"):
                continue
            findings.append(Finding(
                "lint", "fault-gate", f"{rel}:{i + 1}",
                "direct nat_fault_hit() call — fault hooks must go "
                "through NAT_FAULT_POINT so the disabled hot path costs "
                "one predictable branch (no call, no op-counter RMW)"))

    # ---- resacct ----------------------------------------------------------
    # accounted TU: it calls the nat_res macros itself (self-selecting —
    # adopting the first NAT_RES_* in a file turns the rule on for that
    # whole file). nat_res.h only DEFINES the macros and is exempt.
    if os.path.basename(path) != "nat_res.h" and \
            _RES_MACRO.search(scrubbed):
        slines = scrubbed.splitlines()
        for m in _RAW_ALLOC.finditer(scrubbed):
            i = scrubbed.count("\n", 0, m.start())
            # a NAT_RES_ALLOC/FREE/STATIC within 3 lines before or 6
            # after pairs the allocation with its ledger entry (the
            # asymmetry leaves room for the idiomatic error-check block
            # between a syscall/malloc and its accounting)
            lo, hi = max(0, i - 3), min(len(slines), i + 7)
            if any(_RES_MACRO.search(slines[j]) for j in range(lo, hi)):
                continue
            if _allowed(lines, i, "resacct"):
                continue
            # a declared deliberate leak (the refown leak registry) is
            # reviewed surface: same escape contract as static-dtor
            if _leak_declared(lines, i):
                continue
            findings.append(Finding(
                "lint", "resacct", f"{rel}:{i + 1}",
                f"raw allocation {m.group(0).strip()!r} in an accounted "
                f"subsystem TU without a NAT_RES_* accounting call "
                f"nearby — route it through the nat_res ledger or "
                f"escape with natcheck:allow(resacct): why"))

    # ---- sigsafe ----------------------------------------------------------
    # *_sighandler bodies (and the in-file functions they reach) must stay
    # async-signal-safe: BFS the in-file call closure from each handler,
    # then scan every reached body for the forbidden vocabulary.
    if "_sighandler" in scrubbed:
        bodies = _named_function_bodies(scrubbed)
        handler_roots = [n for n in bodies if n.endswith("_sighandler")]
        for root in handler_roots:
            reached = []
            seen = {root}
            queue = [root]
            while queue:
                fn = queue.pop()
                reached.append(fn)
                for cm in re.finditer(r"\b(\w+)\s*\(", bodies[fn][1]):
                    callee = cm.group(1)
                    if callee in bodies and callee not in seen:
                        seen.add(callee)
                        queue.append(callee)
            for fn in reached:
                start_line, body = bodies[fn]
                for fm in _SIGSAFE_FORBID.finditer(body):
                    lineno = start_line + body[:fm.start()].count("\n")
                    if _allowed(lines, lineno - 1, "sigsafe"):
                        continue
                    via = "" if fn == root else f" (reached from {root})"
                    findings.append(Finding(
                        "lint", "sigsafe", f"{rel}:{lineno}",
                        f"{fn}{via} runs in signal context but uses "
                        f"async-signal-UNSAFE operation "
                        f"{fm.group(0).strip()!r} — signal handlers may "
                        f"only use raw syscalls, lock-free atomics and "
                        f"mem* (an interrupted malloc/lock deadlocks)"))

    # ---- seqlock-recheck --------------------------------------------------
    for start_line, body in _function_blocks(scrubbed):
        loads: Dict[str, List[int]] = {}
        for m in _SEQ_LOAD.finditer(body):
            loads.setdefault(m.group(1), []).append(m.start())
        for obj, offs in loads.items():
            if len(offs) >= 2:
                continue
            # payload access on the same object, other than .seq itself
            if not re.search(r"\b%s\s*(?:\.|->)\s*(?!seq\b)\w+"
                             % re.escape(obj), body):
                continue
            # anchor at the seq.load match itself so the reported line is
            # right and the allow() escape on/above that line works
            lineno = start_line + body[:offs[0]].count("\n")
            if _allowed(lines, lineno - 1, "seqlock-recheck"):
                continue
            findings.append(Finding(
                "lint", "seqlock-recheck", f"{rel}:{lineno}",
                f"{obj}.seq is loaded once but {obj}'s payload is read — "
                f"a seqlock reader must re-load the sequence after the "
                f"copy to reject torn reads"))
    return findings


def _scrub(text: str) -> str:
    return "\n".join(_strip_comments_and_strings(ln)
                     for ln in text.splitlines())


def run(src_dir: str = SRC_DIR) -> List[Finding]:
    sources: Dict[str, str] = {}
    for name in sorted(os.listdir(src_dir)):
        if name.endswith((".cpp", ".h", ".cc", ".hpp")):
            p = os.path.join(src_dir, name)
            with open(p, "r", encoding="utf-8", errors="replace") as f:
                sources[p] = f.read()
    # class-body analysis must not see comments/strings: a comment that
    # merely mentions a nontrivial class name must not taint the type
    nontrivial = _nontrivial_classes(
        {p: _scrub(t) for p, t in sources.items()})
    findings: List[Finding] = []
    for path, text in sources.items():
        findings.extend(lint_file(path, text, nontrivial))
    return findings
