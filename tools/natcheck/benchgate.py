"""benchgate — the standing perf regression gate (tools/check.sh --bench).

The flight recorder's third leg (ROADMAP item 5): every gated run
executes ``bench.py`` with the native profiler attached
(BRPC_TPU_BENCH_PROF=1), writes a schema'd artifact — headline lane
values, git sha, the nat_prof flat profile of the loopback lanes, and
the rpcz/native-histogram latency percentiles — then diffs the headline
lanes against the LAST COMMITTED ``BENCH_r*.json`` baseline with
per-lane tolerance bands. A regression beyond a lane's band hard-fails
the gate, and the failure report carries the current run's profile so
the regression arrives with its own flame data attached (the un-blinding
the multicore/fan-out refactors of ROADMAP items 1-2 need).

Tolerance bands: the default band is 15% (the hard-fail contract).
Lanes with measured round-over-round noise on the 1-CPU dev host carry
wider bands (Python-usercode lanes bounce with GIL scheduling; the
worker lane doubled between r04 and r05 from boot-timing alone) — a
wider band is a documented noise floor, not a licence to regress.

Baseline discipline: a COMMITTED ``BENCH_r*.json`` baseline records,
per lane, the MINIMUM over several clean rounds on the recording host —
the credible floor, not one sample. Shared-container scheduling moves
single-run lane values by tens of percent in both directions (r06
measured ±50% between identical back-to-back runs); banding against the
floor keeps the gate quiet on that noise while a real regression (a
code change that halves a lane) still lands far below floor - band.
``make_baseline(artifacts)`` composes the floor from N gated runs.

``compare(baseline, current)`` is a pure function over two artifact
dicts so the golden tests (tests/test_bench_gate.py) can exercise the
clean / one-lane-regressed / missing-lane / schema-drift verdicts
without running a single benchmark.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
from typing import Dict, List, Optional

from tools.natcheck import Finding, REPO_ROOT

SCHEMA = "brpc_tpu-bench-artifact/2"
# /2 only ADDS the extra.contention block (top lock-wait stacks of the
# loopback window) — artifacts of the previous generation stay fully
# comparable, so committed /1 baselines (BENCH_r07) keep gating until a
# fresh round is recorded.
SCHEMA_COMPAT = {"brpc_tpu-bench-artifact/1", SCHEMA}

# artifact written by every gated run (gitignored; the committed
# baseline is the newest BENCH_r*.json carrying the schema field)
DEFAULT_OUT = os.path.join(REPO_ROOT, "BENCH_latest.json")

# headline lane -> relative tolerance band (fraction of the baseline
# value the current run may fall short by before the gate fails)
DEFAULT_TOL = 0.15
HEADLINE_LANES: Dict[str, float] = {
    # native data-path lanes: stable round over round — the 15% contract
    "value": DEFAULT_TOL,               # the headline echo qps
    "epoll_qps": DEFAULT_TOL,
    "async_windowed_qps": DEFAULT_TOL,
    "http_qps": DEFAULT_TOL,
    "grpc_qps": DEFAULT_TOL,
    "redis_qps": DEFAULT_TOL,
    "grpc_client_qps": DEFAULT_TOL,
    "http_client_qps": DEFAULT_TOL,
    # io_uring availability depends on the kernel; when present it is
    # stable, and a 0 baseline (ring refused) skips the row entirely
    "io_uring_qps": DEFAULT_TOL,
    "io_uring_async_qps": DEFAULT_TOL,
    # flight-recorder replay of the committed golden capture (press
    # mode): native data path, but the short window includes capture
    # parse + thread ramp — banded wider until committed rounds prove
    # it as stable as the long-window lanes
    "replay_qps": 0.30,
    # Python-usercode lanes: GIL scheduling noise on the 1-CPU host
    "http_py_qps": 0.30,
    "grpc_py_qps": 0.30,
    "redis_py_qps": 0.30,
    # worker processes add boot/attach timing on top (r04->r05: 2x swing)
    "http_py_workers_qps": 0.50,
    # bulk/transport lanes: dominated by host memcpy bandwidth, which
    # the axon-tunnel cooldown perturbs (BENCH_r04's 0.04 GB/s artifact)
    "stream_GBps": 0.30,
    "native_bulk_GBps": 0.30,
    "shm_desc_GBps": 0.30,
    "shm_desc_small_GBps": 0.50,
    # tensor-fabric RPC push lane (ISSUE 15): the full device-channel
    # path (kind-8 arena write -> descriptor RPC -> lease consume); a
    # Python RPC stack drives it, so the band is the py-lane class.
    # read_arena_grow_GBps reports 0 when the grow path reintroduces
    # the first-touch fault cliff, tripping the band like a collapse.
    "shm_push_GBps": 0.50,
    "read_arena_grow_GBps": 0.50,
    # multicore scaling efficiency (bench.py --cpus N): qps(2cpus) /
    # qps(1cpu) from the pinned two-process lane. On the shared dev
    # container the HOST's own parallel capacity swings 1.3-2.2x run
    # over run (extra.scaling.host_parallel_x records it), so the band
    # is wide; the absolute sublinear check below is the hard floor.
    "cpus2_scaling_x": 0.35,
    # native fan-out lanes (ISSUE 13): the parallel verb to 32 / 1000
    # backends and the swarm churn drill's selective flood. Each lane
    # reports 0 qps when ANY RPC failed (the zero-failed contract), so
    # a failing drill trips the band like a throughput collapse. The
    # Python-comparison lane bounces with GIL scheduling (wide band).
    "fanout_qps": 0.30,
    "fanout1000_qps": 0.50,
    "swarm_qps": 0.30,
    "fanout_py_qps": 0.50,
    # connection-scale drill (ISSUE 14): connections held idle with the
    # live subset at zero failures — the lane reports 0 when ANY RPC
    # failed, the storm left connections unanswered, or a transient
    # subsystem leaked after teardown, so a failing drill trips the
    # band like a throughput collapse
    "conn_scale_conns": DEFAULT_TOL,
    # elastic-capacity drill (ISSUE 20): the autoscaler resizing a
    # dynpart swarm under the replayed golden-capture ramp with a
    # mid-resize SIGKILL. The lane reports the replay qps only when the
    # WHOLE contract held (zero failed RPCs through grows/shrinks/the
    # crash, p99 under the ceiling, capacity tracking offered load), so
    # any contract breach trips the band as a collapse to 0. Ramp-mode
    # replay qps itself carries the replay-lane noise class.
    "autoscale_qps": 0.50,
}

# Latency CEILING lanes: these regress UPWARD — the gate fails when the
# current value exceeds baseline * (1 + band). Composed from the same
# artifacts; extract_lanes carries them beside the throughput lanes.
CEILING_LANES: Dict[str, float] = {
    "fanout_p99_us": 0.50,
    "swarm_p99_us": 0.50,
    # autoscale drill probe p99 (ISSUE 20): paced dynpart probes riding
    # through live resizes — latency regressing upward here means a
    # resize became caller-visible
    "autoscale_p99_us": 0.50,
    # memory-observatory ceilings (ISSUE 14): per-connection accounted
    # bytes (a regression here is a memory-cost regression even when
    # qps holds) and the accept-storm recovery time. Both noisy on the
    # shared container — wide bands; make_baseline takes the MAX.
    "conn_per_conn_bytes": 0.50,
    "conn_accept_storm_s": 1.00,
}

# ABSOLUTE ceiling lanes: gated against a fixed bar, not a baseline —
# the fleet-observatory contract (ISSUE 16) is that a 1Hz builtin.stats
# scrape costs <= 3% of headline qps on ANY host, so no committed
# baseline can relax it. Carried in artifacts/baselines like the
# relative ceilings (make_baseline takes the MAX over clean rounds).
ABS_CEILING_LANES: Dict[str, float] = {
    "fleet_scrape_overhead_pct": 3.0,
}

# Hard sublinear-scaling floor: when the host probe shows real parallel
# headroom (host_parallel_x >= the MIN_HOST bar) and the runtime still
# scales below MIN_X, that is a failing finding regardless of baseline —
# a shared-state bottleneck reintroduced into the write/dispatch path,
# exactly what ROADMAP item 1 forbids. On an overcommitted host (probe
# below the bar) the check is moot: nothing can scale there.
SCALING_ABS_MIN_X = 1.15
SCALING_MIN_HOST_X = 1.6


def extract_lanes(bench: dict) -> Dict[str, float]:
    """Headline lane values out of one bench.py result dict (transport
    lanes live nested under extra.device_lanes; the scaling ratio is
    derived from the extra.scaling curve)."""
    lanes: Dict[str, float] = {}
    extra = bench.get("extra", {}) or {}
    device = extra.get("device_lanes", {}) or {}
    for key in (list(HEADLINE_LANES) + list(CEILING_LANES)
                + list(ABS_CEILING_LANES)):
        if key == "value":
            v = bench.get("value")
        elif key == "cpus2_scaling_x":
            scaling = extra.get("scaling", {}) or {}
            q1, q2 = scaling.get("1"), scaling.get("2")
            v = round(float(q2) / float(q1), 3) \
                if isinstance(q1, (int, float)) and \
                isinstance(q2, (int, float)) and q1 > 0 else None
        else:
            v = extra.get(key, device.get(key))
        if isinstance(v, (int, float)):
            lanes[key] = float(v)
    return lanes


def make_artifact(bench: dict, round_n: int, rc: int = 0,
                  git_sha: str = "") -> dict:
    """Wrap one bench.py result into the schema'd artifact of record."""
    extra = bench.get("extra", {}) or {}
    return {
        "schema": SCHEMA,
        "n": round_n,
        "rc": rc,
        "git_sha": git_sha or _git_sha(),
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
        "lanes": extract_lanes(bench),
        "scaling": extra.get("scaling", {}),
        "rpcz_percentiles": extra.get("native_latency_us", {}),
        "nat_prof": extra.get("nat_prof", {}),
        "contention": extra.get("contention", {}),
        "bench": bench,
    }


def make_baseline(artifacts: List[dict], round_n: int) -> dict:
    """Compose a committable baseline from N clean gated runs: the
    newest run's record (bench/profile/percentiles) with each headline
    lane replaced by its MINIMUM over the runs (the host's credible
    floor — see the module docstring)."""
    clean = [a for a in artifacts if a.get("rc", 0) == 0]
    if not clean:
        raise ValueError("no clean (rc=0) artifacts to compose")
    base = dict(clean[-1])
    floor: Dict[str, float] = {}
    for art in clean:
        for lane, v in (art.get("lanes") or {}).items():
            if lane.endswith("_scaling_x"):
                # scaling ratios record the best ACHIEVED ratio (a
                # crushed shared-host round would otherwise bake an
                # unachievably-low scaling bar into the baseline)
                if lane not in floor or float(v) > floor[lane]:
                    floor[lane] = float(v)
            elif lane in CEILING_LANES or lane in ABS_CEILING_LANES:
                # latency ceilings take the MAXIMUM over clean rounds —
                # the credible worst case plays the floor's role for a
                # lane that regresses upward
                if lane not in floor or float(v) > floor[lane]:
                    floor[lane] = float(v)
            elif lane not in floor or float(v) < floor[lane]:
                floor[lane] = float(v)
    base["lanes"] = floor
    base["n"] = round_n
    base["baseline_runs"] = len(clean)
    return base


def _git_sha() -> str:
    try:
        out = subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO_ROOT,
                             capture_output=True, text=True, timeout=30)
        return out.stdout.strip() if out.returncode == 0 else ""
    except OSError:
        return ""


def find_baseline(repo_root: str = REPO_ROOT) -> Optional[str]:
    """Newest committed BENCH_r*.json that speaks the artifact schema."""
    best_n, best = -1, None
    for name in os.listdir(repo_root):
        m = re.match(r"BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        path = os.path.join(repo_root, name)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("schema") not in SCHEMA_COMPAT:
            continue  # pre-gate rounds (r01..r05) have no lane schema
        if int(m.group(1)) > best_n:
            best_n, best = int(m.group(1)), path
    return best


def _host_cpus(artifact: dict) -> int:
    """CPUs the recording host actually had (bench.py records
    extra.host_cpus); 0 when the artifact predates the field."""
    v = ((artifact.get("bench") or {}).get("extra") or {}).get("host_cpus")
    return v if isinstance(v, int) else 0


def _profile_excerpt(current: dict, lines: int = 12) -> str:
    flat = (current.get("nat_prof") or {}).get("flat") or []
    if not flat:
        return " (no profile attached: run with BRPC_TPU_BENCH_PROF=1)"
    return "; profile of the regressing run:\n      " + "\n      ".join(
        flat[:lines])


def _contention_excerpt(current: dict, lines: int = 6) -> str:
    """Top lock-wait stacks of the regressing run (extra.contention) —
    a lane that slowed down because a lock crept back into the
    write/dispatch path names itself here."""
    collapsed = (current.get("contention") or {}).get("collapsed") or []
    if not collapsed:
        return ""
    return "; top lock-wait stacks:\n      " + "\n      ".join(
        collapsed[:lines])


def compare(baseline: dict, current: dict) -> List[Finding]:
    """Diff two artifacts' headline lanes. Pure function (golden-tested:
    clean / one-lane-regressed / missing-lane / schema-drift)."""
    findings: List[Finding] = []
    where = "tools/check.sh --bench"
    # either side may speak any compatible generation — the bump (/2)
    # only ADDS the contention block, so committed /1 rounds (BENCH_r07)
    # keep gating and re-diffing old artifacts keeps working
    for doc, label in ((baseline, "baseline"), (current, "current")):
        if doc.get("schema") not in SCHEMA_COMPAT:
            findings.append(Finding(
                "bench", "schema-drift", where,
                f"{label} artifact schema is "
                f"{doc.get('schema')!r}, expected {SCHEMA!r} — regenerate "
                f"it with the gate (artifacts of a different schema are "
                f"not comparable)"))
    if findings:
        return findings
    if current.get("rc", 0) != 0:
        findings.append(Finding(
            "bench", "bench-failed", where,
            f"bench.py exited rc={current.get('rc')} — the artifact of "
            f"record is untrustworthy (the BENCH_r05 rc-139 class)"))
        return findings
    base_lanes = baseline.get("lanes", {})
    cur_lanes = current.get("lanes", {})
    for lane, tol in HEADLINE_LANES.items():
        if lane not in base_lanes:
            continue  # lane did not exist at baseline time: nothing to hold
        base_v = float(base_lanes[lane])
        if base_v <= 0:
            continue  # unmeasurable at baseline (e.g. io_uring refused)
        if lane not in cur_lanes:
            if lane == "cpus2_scaling_x" and _host_cpus(current) < 2:
                # a 1-cpu host cannot measure a 2-cpu scaling ratio:
                # unmeasurable on this container, not silently dropped
                # (the io_uring-refused 0-baseline case's twin on the
                # current side)
                continue
            findings.append(Finding(
                "bench", "missing-lane", where,
                f"lane {lane!r} present in the baseline "
                f"({base_v:.1f}) but missing from the current run — a "
                f"silently-dropped lane is a regression, not a skip"
                + _contention_excerpt(current) + _profile_excerpt(current)))
            continue
        cur_v = float(cur_lanes[lane])
        floor = base_v * (1.0 - tol)
        if cur_v < floor:
            drop = 100.0 * (1.0 - cur_v / base_v)
            findings.append(Finding(
                "bench", "regression", where,
                f"lane {lane!r} regressed {drop:.1f}%: {base_v:.1f} -> "
                f"{cur_v:.1f} (tolerance band {tol * 100:.0f}%)"
                + _contention_excerpt(current) + _profile_excerpt(current)))
    # latency ceiling lanes regress UPWARD: current above the committed
    # worst case + band is a tail regression even when qps held
    for lane, tol in CEILING_LANES.items():
        if lane not in base_lanes:
            continue
        base_v = float(base_lanes[lane])
        if base_v <= 0 or lane not in cur_lanes:
            continue  # unmeasured either side (a failing drill already
            # trips through its 0-qps twin lane)
        cur_v = float(cur_lanes[lane])
        ceiling = base_v * (1.0 + tol)
        if cur_v > ceiling:
            rise = 100.0 * (cur_v / base_v - 1.0)
            findings.append(Finding(
                "bench", "regression", where,
                f"latency lane {lane!r} regressed {rise:.1f}% upward: "
                f"{base_v:.1f} -> {cur_v:.1f} us (ceiling band "
                f"{tol * 100:.0f}%)"
                + _contention_excerpt(current) + _profile_excerpt(current)))
    # absolute ceiling lanes: a fixed bar, independent of any baseline
    # (the fleet 1Hz-scrape <=3% contract); missing lane = unmeasured =
    # skip (the bench may run with the fleet lane disabled)
    for lane, bar in ABS_CEILING_LANES.items():
        cur_v = cur_lanes.get(lane)
        if isinstance(cur_v, (int, float)) and float(cur_v) > bar:
            findings.append(Finding(
                "bench", "abs-ceiling", where,
                f"lane {lane!r} measured {float(cur_v):.2f}, above the "
                f"absolute bar {bar:.2f} — the always-on fleet scrape "
                f"contract (ISSUE 16) does not bend with baselines"
                + _contention_excerpt(current) + _profile_excerpt(current)))
    # absolute sublinear-scaling floor (independent of any baseline):
    # the host probe proved parallel headroom, the runtime didn't use it
    scaling_x = cur_lanes.get("cpus2_scaling_x")
    host_x = (current.get("scaling") or {}).get("host_parallel_x")
    if isinstance(scaling_x, (int, float)) and \
            isinstance(host_x, (int, float)) and \
            host_x >= SCALING_MIN_HOST_X and scaling_x < SCALING_ABS_MIN_X:
        disp = (current.get("scaling") or {}).get("disp_stats", {})
        disp_note = ""
        if disp:
            # dispatcher-balance evidence: the per-loop wakeup split at
            # each measured point says whether the loops shared the load
            disp_note = "; per-dispatcher rows: " + "; ".join(
                f"{pt}cpus={rows}" for pt, rows in sorted(disp.items()))
        findings.append(Finding(
            "bench", "sublinear-scaling", where,
            f"2-cpu scaling is {scaling_x:.2f}x while the host's own "
            f"parallel capacity probe measured {host_x:.2f}x — the "
            f"runtime left real cores idle (shared-state bottleneck in "
            f"the write/dispatch path?)" + disp_note
            + _contention_excerpt(current) + _profile_excerpt(current)))
    return findings


def run_bench(timeout_s: int = 2400) -> dict:
    """Execute bench.py with the profiler attached; returns the current
    artifact (rc recorded; the last stdout line is the result JSON)."""
    env = dict(os.environ)
    env["BRPC_TPU_BENCH_PROF"] = "1"
    # scaling curve up to 2 cpus (or however many the host has): the
    # cpus2_scaling_x lane + sublinear check need the {1,2} points
    ncpus = min(2, len(os.sched_getaffinity(0)))
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--cpus", str(ncpus)],
            cwd=REPO_ROOT, capture_output=True, text=True, env=env,
            timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # a wedged bench is the failure class the gate exists to catch:
        # report it through the bench-failed contract, not a traceback
        # (rc mirrors subprocess's killed-by-SIGKILL convention)
        return make_artifact({}, round_n=0, rc=-9)
    bench: dict = {}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                bench = json.loads(line)
                break
            except ValueError:
                continue
    return make_artifact(bench, round_n=0, rc=proc.returncode)


def run(out_path: str = "") -> List[Finding]:
    """The gate: bench -> artifact -> diff vs the committed baseline."""
    out_path = out_path or os.environ.get("BENCH_GATE_OUT", DEFAULT_OUT)
    baseline_path = find_baseline()
    current = run_bench()
    try:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(current, f, indent=1, sort_keys=False)
            f.write("\n")
        print(f"benchgate: artifact written to "
              f"{os.path.relpath(out_path, REPO_ROOT)}")
    except OSError as e:
        print(f"benchgate: could not write artifact: {e}", file=sys.stderr)
    if baseline_path is None:
        # first gated round: nothing schema-comparable committed yet —
        # a failed bench still fails, a clean one records the artifact
        if current.get("rc", 0) != 0:
            return [Finding(
                "bench", "bench-failed", "tools/check.sh --bench",
                f"bench.py exited rc={current.get('rc')} (and no "
                f"schema'd BENCH_r*.json baseline exists yet)")]
        print("benchgate: no schema'd BENCH_r*.json baseline committed "
              "yet — artifact recorded, nothing to diff")
        return []
    with open(baseline_path, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    print(f"benchgate: baseline "
          f"{os.path.relpath(baseline_path, REPO_ROOT)} "
          f"(round {baseline.get('n')}, sha "
          f"{str(baseline.get('git_sha'))[:12]})")
    return compare(baseline, current)
