"""Chaos soak — fixed-seed fault-injection run (``tools/check.sh --chaos``).

Three legs, each a Finding on failure:

1. C smoke (uninstrumented ``nat_smoke``) under ``CHAOS_SPEC`` in the
   ``NAT_FAULT`` environment — the whole smoke (echo sync/async, http,
   redis, shm descriptor rings, its own internal natfault round) runs
   with the ambient fault table armed.
2. The pytest native matrix under the same spec, plus the dedicated
   fault/overload suites (which install their own destructive specs at
   runtime via ``nat_fault_configure`` and restore the env spec after).
3. The ``churn`` round: the rolling-restart drill of
   tests/test_graceful_shutdown.py (3 server processes restarted
   round-robin under a client flood) with DESTRUCTIVE seeds armed in the
   SERVER processes via ``CHURN_SPEC`` — random EPIPE on socket writes
   plus a worker SIGKILL on every worker's 5th shm take. The assertion
   is the graceful-degradation contract itself: zero failed requests
   once retries settle, every SIGTERM'd server exits 0.

``CHAOS_SPEC`` deliberately uses only **semantics-preserving** faults:
short reads/writes (every parser must stay incremental), EINTR on both
directions (the drain/requeue retry arms), connect delays (timeout-clamp
paths) and dropped doorbells (the waiter-gated wake protocol must degrade
to its bounded-timeout polls). Destructive faults — ECONNRESET/EPIPE,
dropped writes, worker SIGKILL — change observable outcomes by design,
so they live in tests that assert the *recovery*, not the absence of the
fault: tests/test_native_fault.py, tests/test_native_overload.py and the
fault-table SIGKILL test in tests/test_shm_worker_crash.py.

Determinism: the fault schedule is a pure function of (seed, site, rule
index, per-site op index) — re-running the lane with the same seed over
the same op sequence replays the same faults. The op *ordering* across
sockets still depends on thread interleaving; the seed pins the
schedule, not the scheduler.

``BRPC_TPU_SANITIZED=1`` is set for the pytest leg so the matrix's
perf/RSS gates loosen — a perturbed run is not a perf regression.

The combined log is written to ``native/CHAOS.md`` — commit it clean.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import List, Tuple

from tools.natcheck import Finding, REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")
CHAOS_MD = os.path.join(NATIVE_DIR, "CHAOS.md")

# The documented fixed-seed chaos spec (see module docstring for why
# only semantics-preserving faults ride the ambient environment).
CHAOS_SPEC = ("seed=42;"
              "read:short:p=0.05;read:err=EINTR:p=0.02;"
              "write:short:p=0.05;write:err=EINTR:p=0.02;"
              "connect:delay_ms=20:p=0.2;"
              "doorbell:drop:p=0.05")

# The churn round's DESTRUCTIVE spec, armed only in the rolling-restart
# drill's server processes (the test asserts recovery, not absence).
CHURN_SPEC = "seed=42;write:err=EPIPE:p=0.002;worker:kill@5"

# The native-lane matrix (the soak set) + the fault/overload suites.
PYTEST_MATRIX = [
    "tests/test_native.py", "tests/test_native_rpc.py",
    "tests/test_native_client.py", "tests/test_native_http.py",
    "tests/test_native_h2.py", "tests/test_native_redis.py",
    "tests/test_native_streaming.py", "tests/test_native_stats.py",
    "tests/test_shm_workers.py", "tests/test_shm_desc_ring.py",
    "tests/test_shm_worker_crash.py",
    "tests/test_native_fault.py", "tests/test_native_overload.py",
    "tests/test_native_cluster.py",
]


def _smoke_leg() -> Tuple[List[Finding], str]:
    findings: List[Finding] = []
    try:
        subprocess.run(["make", "-C", NATIVE_DIR, "nat_smoke"], check=True,
                       capture_output=True, timeout=900)
    except subprocess.CalledProcessError as e:
        findings.append(Finding(
            "chaos", "smoke-build", "native/Makefile",
            "build failed: " +
            (e.stderr or b"").decode(errors="replace")[-800:]))
        return findings, "chaos smoke: BUILD FAILED"
    env = dict(os.environ)
    env["NAT_FAULT"] = CHAOS_SPEC
    try:
        proc = subprocess.run(
            [os.path.join(NATIVE_DIR, "nat_smoke")], capture_output=True,
            timeout=600, env=env)
    except subprocess.TimeoutExpired:
        # a hang under injected faults IS the defect class this hunts
        findings.append(Finding(
            "chaos", "smoke-hang", "native/nat_smoke",
            "chaos smoke timed out under NAT_FAULT (hang/deadlock?)"))
        return findings, "chaos smoke: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        findings.append(Finding(
            "chaos", "smoke", "native/nat_smoke",
            f"chaos smoke exited rc={proc.returncode}: "
            f"{out.strip()[-400:]}"))
    return findings, out


def _pytest_leg() -> Tuple[List[Finding], str]:
    findings: List[Finding] = []
    env = dict(os.environ)
    env["NAT_FAULT"] = CHAOS_SPEC
    env["BRPC_TPU_SANITIZED"] = "1"  # loosen perf/RSS gates: perturbed
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", *PYTEST_MATRIX, "-q", "-m",
             "not slow", "-p", "no:cacheprovider"],
            capture_output=True, timeout=1800, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("chaos", "pytest-hang", "tests/",
                        "chaos python matrix timed out")], \
            "chaos pytest: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        tail = out.strip().splitlines()[-1] if out.strip() else "?"
        findings.append(Finding(
            "chaos", "pytest", "tests/",
            f"chaos python matrix rc={proc.returncode}: {tail}"))
    return findings, out


def _churn_leg() -> Tuple[List[Finding], str]:
    """Seeded rolling-restart drill: servers run under CHURN_SPEC, the
    client flood must settle with zero failures (the two-process churn
    acceptance test of the graceful-drain lifecycle)."""
    findings: List[Finding] = []
    env = dict(os.environ)
    env.pop("NAT_FAULT", None)  # the CLIENT side stays clean; servers
    env["BRPC_TPU_CHURN_FAULT"] = CHURN_SPEC  # arm via the test hook
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_graceful_shutdown.py", "-q",
             "-k", "churn or rolling_restart or sigterm",
             "-p", "no:cacheprovider"],
            capture_output=True, timeout=900, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("chaos", "churn-hang", "tests/",
                        "churn round timed out (drain wedged?)")], \
            "chaos churn: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        tail = out.strip().splitlines()[-1] if out.strip() else "?"
        findings.append(Finding(
            "chaos", "churn", "tests/test_graceful_shutdown.py",
            f"churn round rc={proc.returncode}: {tail}"))
    return findings, out


def _swarm_leg() -> Tuple[List[Finding], str]:
    """Swarm round (ISSUE 13): the multi-port fan-out churn drill
    (tests/test_native_cluster.py's slow acceptance) with DESTRUCTIVE
    seeds armed in every swarm SERVER process — random EPIPE on socket
    writes plus the worker-kill seed — while the cluster client stays
    clean. The assertion is the fan-out contract itself: zero failed
    RPCs through rolling SIGTERM restarts + live naming updates."""
    findings: List[Finding] = []
    env = dict(os.environ)
    env.pop("NAT_FAULT", None)  # the CLIENT side stays clean; servers
    env["BRPC_TPU_CHURN_FAULT"] = CHURN_SPEC  # armed via the bench hook
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_native_cluster.py", "-q",
             "-k", "swarm_churn or membership_updates",
             "-p", "no:cacheprovider"],
            capture_output=True, timeout=900, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("chaos", "swarm-hang", "tests/",
                        "swarm round timed out (fan-out wedged?)")], \
            "chaos swarm: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        tail = out.strip().splitlines()[-1] if out.strip() else "?"
        findings.append(Finding(
            "chaos", "swarm", "tests/test_native_cluster.py",
            f"swarm round rc={proc.returncode}: {tail}"))
    return findings, out


def _resize_leg() -> Tuple[List[Finding], str]:
    """Resize round (ISSUE 20): the dynpart resize-under-fault matrix
    (tests/test_dynpart_native.py) — swarm members added/retired live so
    the partition scheme set resizes mid-flood, with DESTRUCTIVE seeds
    armed in every member process (EPIPE write storms) and a SIGKILL
    landing mid-resize. The assertion is the elastic-capacity contract:
    a resize is never caller-visible and zero calls fail once the
    bounded retry settles."""
    findings: List[Finding] = []
    env = dict(os.environ)
    env.pop("NAT_FAULT", None)  # the CLIENT side stays clean; servers
    env["BRPC_TPU_CHURN_FAULT"] = CHURN_SPEC  # armed via the pool hook
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_dynpart_native.py", "-q",
             "-k", "resize_under_fault",
             "-p", "no:cacheprovider"],
            capture_output=True, timeout=900, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("chaos", "resize-hang", "tests/",
                        "resize round timed out (publish wedged?)")], \
            "chaos resize: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        tail = out.strip().splitlines()[-1] if out.strip() else "?"
        findings.append(Finding(
            "chaos", "resize", "tests/test_dynpart_native.py",
            f"resize round rc={proc.returncode}: {tail}"))
    return findings, out


def run(write_log: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    sections = []
    t0 = time.time()
    got, out = _smoke_leg()
    findings.extend(got)
    sections.append(("C smoke under NAT_FAULT", out))
    got, out = _pytest_leg()
    findings.extend(got)
    sections.append(("pytest native matrix under NAT_FAULT", out))
    got, out = _churn_leg()
    findings.extend(got)
    sections.append(("churn round (rolling restart under %s)" %
                     CHURN_SPEC, out))
    got, out = _swarm_leg()
    findings.extend(got)
    sections.append(("swarm round (fan-out churn under %s)" %
                     CHURN_SPEC, out))
    got, out = _resize_leg()
    findings.extend(got)
    sections.append(("resize round (dynpart resize-under-fault under %s)" %
                     CHURN_SPEC, out))

    if write_log:
        with open(CHAOS_MD, "w", encoding="utf-8") as f:
            f.write("# native chaos soak log\n\n")
            f.write("Produced by `tools/check.sh --chaos` "
                    "(tools/natcheck/chaos.py). The C smoke and the\n"
                    "pytest native matrix run with the fixed-seed fault "
                    "spec below armed via the\n`NAT_FAULT` environment; "
                    "the dedicated fault/overload suites additionally\n"
                    "install destructive specs at runtime and assert the "
                    "recovery paths.\nThe churn round runs the "
                    "rolling-restart drill (SIGTERM drain + failover)\n"
                    "with the destructive churn spec armed in the server "
                    "processes.\n\n")
            f.write("Spec: `%s`\n" % CHAOS_SPEC)
            f.write("Churn spec (server processes): `%s`\n\n" % CHURN_SPEC)
            f.write("Result: %s (%d finding(s), %.0fs)\n\n" %
                    ("CLEAN" if not findings else "FAILING",
                     len(findings), time.time() - t0))
            for f2 in findings:
                f.write("- FINDING: %s\n" % f2)
            for title, body in sections:
                tail = "\n".join(body.strip().splitlines()[-25:])
                f.write("\n## %s\n\n```\n%s\n```\n" % (title, tail))
    return findings
