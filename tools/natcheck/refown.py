"""refown — declared ownership/refcount contract verification.

The native runtime's reference-counting discipline (NatSocket's
Address/SetFailed borrow protocol, IOBuf block refs, arena span pins,
WriteReq pool nodes, admission tokens, drain-role-held refs) is declared
through the ``NAT_REF_*`` macro grammar of ``native/src/nat_refown.h``:
every acquire names the TAG that will release it, transfers move
ownership without a count change, borrows mark non-owning uses, and
``NAT_REF_DEAD`` marks destruction/recycle points. This pass parses
every TU, builds the acquire/release/transfer graph per tag — with
transitive call closure, fiber/function-pointer handoffs and lambda
bodies counted as release points — and fails on unbalanced contracts.

Rules (suppress with ``// natcheck:allow(<rule>): why``):

- ``refown-undeclared-tag``: a NAT_REF_* site uses a tag not declared in
  nat_refown.h's NAT_REF_TAG table.
- ``refown-no-release``: a tag is acquired (or transferred INTO)
  somewhere but no release (or transfer OUT) of it exists anywhere —
  the reference can never be retired.
- ``refown-no-acquire``: a release/transfer-out of a tag that is never
  acquired/transferred-in — a release with no owning acquire.
- ``refown-leak-path``: inside a function that both acquires a tag and
  (directly, via a callee's closure, via a function handed off by name,
  or via a lambda body) releases it, an early ``return`` between the
  acquire and the first reachable release leaks the held tag.
- ``refown-double-release``: two straight-line releases of the same
  (object, tag) with no intervening acquire / branch boundary.
- ``refown-borrow-after-release``: a ``NAT_REF_BORROW(x)`` reachable in
  straight line after a release of ``x`` — use after the owning
  reference was dropped.
- ``refown-raw``: a raw ``add_ref()`` / ``release()`` call outside the
  macro surface (the definitions themselves and nat_refown.h are
  exempt) — every count change must carry its owner tag.
- ``refown-leak-undeclared``: a deliberately-leaked static (the
  ``T& x = *new T`` / ``static T* x = new T`` idioms) without a
  ``natcheck:leak(symbol): why`` declaration.
- ``refown-lsan-unbacked``: a ``leak:`` entry in native/lsan.supp whose
  symbol is not backed by any ``natcheck:leak`` declaration — the
  suppression and the source annotation must stay one source of truth.
"""
from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

if __package__ in (None, ""):  # `python tools/natcheck/refown.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

from tools.natcheck import Finding, REPO_ROOT  # noqa: E402
from tools.natcheck.lockorder import (  # noqa: E402
    _dedupe, _strip_comments_and_strings, collect_sources,
    parse_functions, FuncInfo)

SRC_DIR = os.path.join(REPO_ROOT, "native", "src")
REFOWN_HEADER = "nat_refown.h"
LSAN_SUPP = os.path.join(REPO_ROOT, "native", "lsan.supp")

_ALLOW = re.compile(r"natcheck:allow\(([a-z-]+)\)")
_TAG_DECL = re.compile(r"\bNAT_REF_TAG\(\s*([\w.]+)\s*,")
_LEAK_DECL = re.compile(r"natcheck:leak\(([\w:.\-]+)\)")
_MACRO = re.compile(
    r"\bNAT_REF_(ACQUIRE|ACQUIRED|RELEASE|RELEASED|TRANSFER|BORROW|DEAD)"
    r"\s*\(")
_LEAK_IDIOM = re.compile(
    r"&\s*\w+\s*=\s*\*\s*new\b|\bstatic\s+\w[\w:<>,\s]*\*\s*\w+\s*=\s*new\b")
# raw count-change call: optional receiver, empty parens. The receiver
# group keeps `wreq_release()`-style OTHER names from matching via \b.
_RAW_CALL = re.compile(
    r"(?:([\w\]\)]+)\s*(?:->|\.)\s*)?\b(add_ref|release)\s*\(\s*\)")
_RETURN = re.compile(r"\breturn\b")

ACQ_KINDS = ("ACQUIRE", "ACQUIRED")
REL_KINDS = ("RELEASE", "RELEASED")


class Site:
    """One NAT_REF_* macro site."""

    def __init__(self, kind: str, obj: str, tags: Tuple[str, ...],
                 path: str, line: int, pos: int = -1):
        self.kind = kind
        self.obj = obj          # normalized object expression
        self.tags = tags        # 1 tag; TRANSFER: (from, to); BORROW/DEAD: ()
        self.path = path
        self.line = line
        self.pos = pos          # offset within the enclosing body (local)


def _balanced_args(text: str, open_idx: int) -> Tuple[str, int]:
    depth = 0
    for k in range(open_idx, len(text)):
        if text[k] == "(":
            depth += 1
        elif text[k] == ")":
            depth -= 1
            if depth == 0:
                return text[open_idx + 1:k], k
    return text[open_idx + 1:], len(text)


def _split_args(args: str) -> List[str]:
    out, depth, cur = [], 0, []
    for ch in args:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def _norm_obj(expr: str) -> str:
    """`refs_[begin_ + i].block` -> block, `&d` -> d, `this` -> this,
    `nat_ref_adm_anchor()` -> nat_ref_adm_anchor."""
    expr = expr.strip().rstrip(")").replace("(", " ")
    expr = re.sub(r"\[[^\]]*\]", "", expr)
    m = re.search(r"([A-Za-z_]\w*)\s*$", expr)
    return m.group(1) if m else expr


def _sites_in(text: str, path: str, line_base: int = 1,
              pos_base: int = 0) -> List[Site]:
    out = []
    for m in _MACRO.finditer(text):
        kind = m.group(1)
        args, _ = _balanced_args(text, m.end() - 1)
        parts = _split_args(args)
        obj = _norm_obj(parts[0]) if parts else ""
        if kind == "TRANSFER":
            tags = tuple(p for p in parts[1:3])
        elif kind in ("BORROW", "DEAD"):
            tags = ()
        else:
            tags = (parts[1],) if len(parts) > 1 else ("",)
        out.append(Site(kind, obj, tags, path,
                        line_base + text.count("\n", 0, m.start()),
                        pos_base + m.start()))
    return out


def _allowed(lines: List[str], i: int, rule: str) -> bool:
    """allow() on the same line or the contiguous comment block above."""
    if 0 <= i < len(lines):
        m = _ALLOW.search(lines[i])
        if m and m.group(1) == rule:
            return True
    j = i - 1
    while j >= 0 and i - j <= 8:
        stripped = lines[j].strip()
        if not stripped.startswith("//") and not stripped.startswith("#"):
            break
        m = _ALLOW.search(lines[j])
        if m and m.group(1) == rule:
            return True
        j -= 1
    return False


def _leak_declared(lines: List[str], i: int) -> bool:
    """A natcheck:leak declaration on the line itself or in the
    CONTIGUOUS comment block attached above it — an unrelated
    declaration past intervening code must not excuse this one."""
    if 0 <= i < len(lines) and _LEAK_DECL.search(lines[i]):
        return True
    j = i - 1
    while j >= 0 and i - j <= 8:
        stripped = lines[j].strip()
        if not stripped.startswith("//") and not stripped.startswith("#"):
            break
        if _LEAK_DECL.search(lines[j]):
            return True
        j -= 1
    return False


def parse_tag_table(src_dir: str) -> Set[str]:
    p = os.path.join(src_dir, REFOWN_HEADER)
    if not os.path.exists(p):
        p = os.path.join(SRC_DIR, REFOWN_HEADER)
    tags: Set[str] = set()
    if os.path.exists(p):
        with open(p, "r", encoding="utf-8", errors="replace") as f:
            for m in _TAG_DECL.finditer(f.read()):
                tags.add(m.group(1))
    return tags


_CALL_NAME = re.compile(r"\b([A-Za-z_]\w*)\b")


def _function_release_sets(
        all_fns: Dict[str, List[FuncInfo]]) -> Dict[str, Set[str]]:
    """name -> tags the function (transitively) releases/transfers-out."""
    direct: Dict[str, Set[str]] = {}
    calls: Dict[str, Set[str]] = {}
    for name, fns in all_fns.items():
        rel: Set[str] = set()
        callees: Set[str] = set()
        for fn in fns:
            for st in _sites_in(fn.body, fn.path):
                if st.kind in REL_KINDS:
                    rel.add(st.tags[0])
                elif st.kind == "TRANSFER" and len(st.tags) == 2:
                    rel.add(st.tags[0])
            for cm in _CALL_NAME.finditer(fn.body):
                callees.add(cm.group(1))
        direct[name] = rel
        calls[name] = callees
    changed = True
    while changed:
        changed = False
        for name in direct:
            for callee in calls[name]:
                if callee == name or callee not in direct:
                    continue
                extra = direct[callee] - direct[name]
                if extra:
                    direct[name] |= extra
                    changed = True
    return direct


def check(src_dir: str = SRC_DIR,
          lsan_path: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    sources = collect_sources(src_dir)
    declared_tags = parse_tag_table(src_dir)

    all_sites: List[Site] = []
    file_lines: Dict[str, List[str]] = {}
    fns_by_name: Dict[str, List[FuncInfo]] = {}
    fns_by_file: Dict[str, List[FuncInfo]] = {}
    leak_decls: Set[str] = set()

    for path, text in sources.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = text.splitlines()
        file_lines[path] = lines
        for m in _LEAK_DECL.finditer(text):
            leak_decls.add(m.group(1))
        scrubbed = "\n".join(_strip_comments_and_strings(ln)
                             for ln in lines)
        if os.path.basename(path) != REFOWN_HEADER:
            all_sites.extend(_sites_in(scrubbed, path))
        flist = parse_functions(path, text)
        fns_by_file[path] = flist
        for fn in flist:
            fns_by_name.setdefault(fn.name, []).append(fn)

    # ---- tag declaration + global pairing ---------------------------------
    acquired: Dict[str, List[Site]] = {}
    released: Dict[str, List[Site]] = {}
    for st in all_sites:
        if st.kind in ACQ_KINDS:
            acquired.setdefault(st.tags[0], []).append(st)
        elif st.kind in REL_KINDS:
            released.setdefault(st.tags[0], []).append(st)
        elif st.kind == "TRANSFER" and len(st.tags) == 2:
            released.setdefault(st.tags[0], []).append(st)
            acquired.setdefault(st.tags[1], []).append(st)
        for tag in st.tags:
            if tag and tag not in declared_tags:
                rel = os.path.relpath(st.path, REPO_ROOT)
                if not _allowed(file_lines[st.path], st.line - 1,
                                "refown-undeclared-tag"):
                    findings.append(Finding(
                        "refown", "refown-undeclared-tag",
                        f"{rel}:{st.line}",
                        f"tag `{tag}` is not declared in "
                        f"{REFOWN_HEADER}'s NAT_REF_TAG table"))
    for tag, sites in acquired.items():
        if tag in released:
            continue
        st = sites[0]
        rel = os.path.relpath(st.path, REPO_ROOT)
        if _allowed(file_lines[st.path], st.line - 1, "refown-no-release"):
            continue
        findings.append(Finding(
            "refown", "refown-no-release", f"{rel}:{st.line}",
            f"tag `{tag}` is acquired here but no release/transfer-out "
            f"of it exists anywhere — the reference can never be "
            f"retired"))
    for tag, sites in released.items():
        if tag in acquired:
            continue
        st = sites[0]
        rel = os.path.relpath(st.path, REPO_ROOT)
        if _allowed(file_lines[st.path], st.line - 1, "refown-no-acquire"):
            continue
        findings.append(Finding(
            "refown", "refown-no-acquire", f"{rel}:{st.line}",
            f"tag `{tag}` is released here but never acquired/"
            f"transferred-in — a release with no owning acquire"))

    # ---- per-function path rules ------------------------------------------
    release_sets = _function_release_sets(fns_by_name)
    for path, flist in fns_by_file.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = file_lines[path]
        for fn in flist:
            _check_function(fn, rel, lines, flist, release_sets, findings)

    # ---- raw add_ref()/release() outside the macro surface ----------------
    for path, text in sources.items():
        if os.path.basename(path) == REFOWN_HEADER:
            continue
        rel = os.path.relpath(path, REPO_ROOT)
        lines = file_lines[path]
        for i, ln in enumerate(_strip_comments_and_strings(ln)
                               for ln in lines):
            for m in _RAW_CALL.finditer(ln):
                # definition/declaration, not a call: `void release() {`,
                # `void NatSocket::release() {`, `void release();`
                before = ln[:m.start()]
                if re.search(r"\bvoid\s+[\w:]*$", before):
                    continue
                if _allowed(lines, i, "refown-raw"):
                    continue
                findings.append(Finding(
                    "refown", "refown-raw", f"{rel}:{i + 1}",
                    f"raw {m.group(2)}() call outside the NAT_REF_* "
                    f"macro surface — every count change must name the "
                    f"tag that owns it (nat_refown.h)"))

    # ---- declared-leak registry -------------------------------------------
    for path, text in sources.items():
        rel = os.path.relpath(path, REPO_ROOT)
        lines = file_lines[path]
        for i, ln in enumerate(_strip_comments_and_strings(ln)
                               for ln in lines):
            if not _LEAK_IDIOM.search(ln):
                continue
            if _leak_declared(lines, i):
                continue
            if _allowed(lines, i, "refown-leak-undeclared"):
                continue
            findings.append(Finding(
                "refown", "refown-leak-undeclared", f"{rel}:{i + 1}",
                "deliberately-leaked static without a "
                "`natcheck:leak(symbol): why` declaration — the leak "
                "registry (this rule, the static-dtor lint and "
                "native/lsan.supp) shares one source of truth"))
    lsan = lsan_path if lsan_path is not None else LSAN_SUPP
    if os.path.exists(lsan):
        with open(lsan, "r", encoding="utf-8", errors="replace") as f:
            for i, ln in enumerate(f):
                ln = ln.strip()
                if not ln.startswith("leak:"):
                    continue
                sym = ln[len("leak:"):].strip()
                base = sym[len("brpc_tpu::"):] if sym.startswith(
                    "brpc_tpu::") else sym
                if base in leak_decls or sym in leak_decls:
                    continue
                findings.append(Finding(
                    "refown", "refown-lsan-unbacked",
                    f"{os.path.relpath(lsan, REPO_ROOT)}:{i + 1}",
                    f"lsan suppression `{sym}` has no backing "
                    f"`natcheck:leak({base})` declaration in the "
                    f"sources — prune it or declare the leak"))
    return _dedupe(findings)


def _check_function(fn: FuncInfo, rel: str, lines: List[str],
                    file_fns: List[FuncInfo],
                    release_sets: Dict[str, Set[str]],
                    findings: List[Finding]) -> None:
    body = fn.body
    sites = _sites_in(body, fn.path, line_base=fn.start_line)

    def lineno(off: int) -> int:
        return fn.start_line + body[:off].count("\n")

    # lambdas extracted from this body count as handoff release points at
    # their offset (the lambda runs later, on whatever thread/fiber the
    # handoff targets — exactly the "released by the sweep fiber" shape)
    lam_events: List[Tuple[int, Set[str]]] = []
    for lf in file_fns:
        if lf.name == fn.name + "<lambda>" and \
                fn.body_off <= lf.body_off <= fn.body_off + len(body):
            rels: Set[str] = set()
            for st in _sites_in(lf.body, lf.path):
                if st.kind in REL_KINDS:
                    rels.add(st.tags[0])
                elif st.kind == "TRANSFER" and len(st.tags) == 2:
                    rels.add(st.tags[0])
            if rels:
                lam_events.append((lf.body_off - fn.body_off, rels))

    acqs = [st for st in sites if st.kind in ACQ_KINDS]
    rels = [st for st in sites if st.kind in REL_KINDS]
    xfers = [st for st in sites if st.kind == "TRANSFER"
             and len(st.tags) == 2]

    # ---- refown-leak-path -------------------------------------------------
    for acq in acqs:
        tag = acq.tags[0]
        events = [st.pos for st in rels if st.tags[0] == tag]
        events += [st.pos for st in xfers if st.tags[0] == tag]
        events += [off for off, tags in lam_events if tag in tags]
        # callees (or function names handed off as arguments) whose
        # transitive closure releases the tag
        for name, relset in release_sets.items():
            if name == fn.name or tag not in relset:
                continue
            for m in re.finditer(r"\b%s\b" % re.escape(name), body):
                events.append(m.start())
        events = sorted(e for e in events if e > acq.pos)
        if not events:
            continue  # cross-function contract: global pairing covers it
        first_rel = events[0]
        for m in _RETURN.finditer(body, acq.pos, first_rel):
            ln = lineno(m.start())
            if _allowed(lines, ln - 1, "refown-leak-path"):
                continue
            findings.append(Finding(
                "refown", "refown-leak-path", f"{rel}:{ln}",
                f"early return leaks tag `{tag}` acquired at line "
                f"{acq.line} (no release/transfer/handoff reaches this "
                f"arm)"))

    # ---- refown-double-release (straight-line) ----------------------------
    by_key: Dict[Tuple[str, str], List[Site]] = {}
    for st in rels:
        by_key.setdefault((st.obj, st.tags[0]), []).append(st)
    for (obj, tag), group in by_key.items():
        group.sort(key=lambda s: s.pos)
        for a, b in zip(group, group[1:]):
            between = body[a.pos:b.pos]
            if "{" in between or "}" in between or \
                    _RETURN.search(between):
                continue
            if any(st.pos > a.pos and st.pos < b.pos and
                   st.kind in ACQ_KINDS and st.tags[0] == tag and
                   st.obj == obj for st in sites):
                continue
            if any(st.pos > a.pos and st.pos < b.pos and
                   st.kind == "TRANSFER" and st.tags[1] == tag
                   for st in sites):
                continue
            if _allowed(lines, b.line - 1, "refown-double-release"):
                continue
            findings.append(Finding(
                "refown", "refown-double-release", f"{rel}:{b.line}",
                f"straight-line double release of `{obj}` tag `{tag}` "
                f"(first at line {a.line}) with no intervening "
                f"acquire"))

    # ---- refown-borrow-after-release --------------------------------------
    for st in sites:
        if st.kind != "BORROW":
            continue
        for r in rels:
            if r.obj != st.obj or r.pos >= st.pos:
                continue
            between = body[r.pos:st.pos]
            if "{" in between or "}" in between:
                continue
            if any(a.pos > r.pos and a.pos < st.pos and
                   a.kind in ACQ_KINDS and a.obj == st.obj
                   for a in sites):
                continue
            if _allowed(lines, st.line - 1, "refown-borrow-after-release"):
                continue
            findings.append(Finding(
                "refown", "refown-borrow-after-release",
                f"{rel}:{st.line}",
                f"`{st.obj}` borrowed after its reference was released "
                f"at line {r.line}"))


def run(src_dir: str = SRC_DIR) -> List[Finding]:
    return check(src_dir)


if __name__ == "__main__":
    src = SRC_DIR
    for a in sys.argv[1:]:
        src = a
    fs = check(src)
    for f in fs:
        print(f)
    sys.exit(1 if fs else 0)
