"""ABI/FFI contract checker — the ctypes layer vs the compiled truth.

The native build emits a manifest (native/nat_abi, generated from the
real declarations in nat_api.h via decltype/offsetof) describing every
exported symbol's signature and every shared struct's layout. This pass:

1. statically parses the ctypes binding sources (``lib.<sym>.argtypes =
   [...]`` / ``.restype = ...`` assignments and ``ctypes.Structure``
   subclasses) with ``ast`` — no import of the bound library needed, so
   golden tests can point it at perturbed copies;
2. diffs those declarations against the manifest (canonical type names,
   struct sizeof/offsetof/field types);
3. diffs the manifest's symbol set against ``nm -D`` of the built .so, so
   an export added without a nat_api.h declaration (or a stale .so) fails.

Canonical type names match nat_abi.cpp: i8 u8 i16 u16 i32 u32 i64 u64
f32 f64 char void fnptr ptr:<T> arr:<N>:<T> struct:<Name>.
"""
from __future__ import annotations

import ast
import ctypes
import json
import os
import subprocess
from typing import Dict, List, Optional, Tuple

from tools.natcheck import Finding, REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")
DEFAULT_BINDINGS = [
    os.path.join(REPO_ROOT, "brpc_tpu", "native", "__init__.py"),
    os.path.join(REPO_ROOT, "brpc_tpu", "bvar", "native_vars.py"),
]

# Exported symbols with NO ctypes declaration, on purpose: consumed only
# by the native-side harnesses (bench_main / nat_smoke) through nat_api.h.
# Any other manifest symbol missing from every binding file is a finding
# — an export reached through ctypes' attribute fallback would run with
# the default c_int restype and unchecked arguments.
UNBOUND_OK = {
    "nat_io_counters",           # bench_main io-per-request stats
    "nat_rpc_client_bench_bulk", # bench_main bulk lane
    "nat_http_acall",            # native async http (C embedders only)
    "nat_grpc_acall",            # native async grpc (C embedders only)
}

# ---------------------------------------------------------------------------
# manifest + nm
# ---------------------------------------------------------------------------


def build_manifest(native_dir: str = NATIVE_DIR) -> dict:
    """Build (if needed) and run the manifest generator."""
    subprocess.run(["make", "-C", native_dir, "nat_abi"], check=True,
                   capture_output=True, timeout=600)
    out = subprocess.run([os.path.join(native_dir, "nat_abi")], check=True,
                         capture_output=True, timeout=60)
    return json.loads(out.stdout)


def so_exports(so_path: str) -> Optional[set]:
    """nat_* symbols exported by the .so, or None when nm is unavailable."""
    try:
        out = subprocess.run(["nm", "-D", "--defined-only", so_path],
                             check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.CalledProcessError):
        return None
    syms = set()
    for line in out.stdout.decode(errors="replace").splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[1] == "T" and \
                parts[2].startswith("nat_"):
            syms.add(parts[2])
    return syms


# ---------------------------------------------------------------------------
# ctypes expression evaluation + canonicalization
# ---------------------------------------------------------------------------

_SCALARS: Dict[type, str] = {
    ctypes.c_bool: "u8",
    ctypes.c_byte: "i8",
    ctypes.c_ubyte: "u8",
    ctypes.c_short: "i16",
    ctypes.c_ushort: "u16",
    ctypes.c_int: "i32",
    ctypes.c_uint: "u32",
    ctypes.c_long: "i64" if ctypes.sizeof(ctypes.c_long) == 8 else "i32",
    ctypes.c_ulong: "u64" if ctypes.sizeof(ctypes.c_ulong) == 8 else "u32",
    ctypes.c_longlong: "i64",
    ctypes.c_ulonglong: "u64",
    ctypes.c_float: "f32",
    ctypes.c_double: "f64",
    ctypes.c_char: "char",
}
# width-aliases (c_int32 is c_int, c_size_t is c_ulong, ...) collapse via
# identity in _SCALARS already; nothing more to do.


def canon(t) -> str:
    """Canonical type name of a ctypes declaration (None = void)."""
    if t is None:
        return "void"
    if t is ctypes.c_char_p:
        return "ptr:char"
    if t is ctypes.c_void_p:
        return "ptr:void"
    if t in _SCALARS:
        return _SCALARS[t]
    if isinstance(t, type):
        if issubclass(t, ctypes._Pointer):  # POINTER(X)
            return "ptr:" + canon(t._type_)
        if issubclass(t, ctypes.Array):
            return f"arr:{t._length_}:" + canon(t._type_)
        if issubclass(t, ctypes.Structure):
            return "struct:" + t.__name__
        if issubclass(t, ctypes._CFuncPtr):
            return "fnptr"
    return f"unknown:{t!r}"


def compatible(py: str, c: str) -> bool:
    """Is the ctypes-side canonical type an acceptable mirror of the C one?

    Exact match, or the opaque-pointer idioms: c_void_p stands in for any
    pointer (handles), and a CFUNCTYPE thunk satisfies a C function
    pointer (or void*) parameter.
    """
    if py == c:
        return True
    is_ptr = lambda s: s.startswith("ptr:") or s == "fnptr"  # noqa: E731
    if py == "ptr:void" and is_ptr(c):
        return True
    if py == "fnptr" and (c == "fnptr" or c == "ptr:void"):
        return True
    return False


# ---------------------------------------------------------------------------
# static parse of the binding sources
# ---------------------------------------------------------------------------


class Bindings:
    """What one Python source declares about the FFI surface."""

    def __init__(self):
        # symbol -> (lineno, [ctypes]) / (lineno, ctype-or-None)
        self.argtypes: Dict[str, Tuple[int, list]] = {}
        self.restype: Dict[str, Tuple[int, object]] = {}
        # struct name -> (lineno, ctypes.Structure subclass)
        self.structs: Dict[str, Tuple[int, type]] = {}


def parse_bindings(path: str) -> Tuple[Bindings, List[Finding]]:
    findings: List[Finding] = []
    b = Bindings()
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    env = {"ctypes": ctypes}

    def ev(node):
        return eval(compile(ast.Expression(node), path, "eval"), env)  # noqa: S307

    # module-level constants that structs/declarations may reference
    # (e.g. ACALL_CB = ctypes.CFUNCTYPE(...), METHOD_LEN = 48): evaluated
    # FIRST, best-effort, order-preserving.
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            try:
                env[node.targets[0].id] = ev(node.value)
            except Exception:
                pass

    for node in ast.walk(tree):
        # class X(ctypes.Structure): _fields_ = [...]
        if isinstance(node, ast.ClassDef):
            is_struct = any(
                (isinstance(base, ast.Attribute) and
                 base.attr == "Structure") or
                (isinstance(base, ast.Name) and base.id == "Structure")
                for base in node.bases)
            if not is_struct:
                continue
            fields = None
            bad = None
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "_fields_"
                        for t in stmt.targets):
                    try:
                        fields = ev(stmt.value)
                    except Exception as e:
                        bad = e
            if fields is None:
                findings.append(Finding(
                    "abi", "struct-parse", f"{path}:{node.lineno}",
                    f"ctypes.Structure {node.name}: could not evaluate "
                    f"_fields_ ({bad})" if bad else
                    f"ctypes.Structure {node.name} has no literal "
                    f"_fields_"))
                continue
            cls = type(node.name, (ctypes.Structure,),
                       {"_fields_": fields})
            env[node.name] = cls
            b.structs[node.name] = (node.lineno, cls)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute) and
                tgt.attr in ("argtypes", "restype") and
                isinstance(tgt.value, ast.Attribute)):
            continue
        sym = tgt.value.attr
        if not sym.startswith("nat_"):
            continue
        try:
            val = ev(node.value)
        except Exception as e:
            findings.append(Finding(
                "abi", "decl-parse", f"{path}:{node.lineno}",
                f"could not evaluate {sym}.{tgt.attr}: {e}"))
            continue
        if tgt.attr == "argtypes":
            b.argtypes[sym] = (node.lineno, list(val))
        else:
            b.restype[sym] = (node.lineno, val)
    return b, findings


# ---------------------------------------------------------------------------
# the check
# ---------------------------------------------------------------------------


def check_abi(manifest: dict, binding_paths: List[str],
              exports: Optional[set] = None) -> List[Finding]:
    findings: List[Finding] = []
    symbols: Dict[str, dict] = manifest.get("symbols", {})
    structs: Dict[str, dict] = manifest.get("structs", {})

    # manifest vs nm: both directions must agree
    if exports is not None:
        for s in sorted(exports - set(symbols)):
            findings.append(Finding(
                "abi", "unmanifested-export", "native/src/nat_api.h",
                f"{s} is exported by the .so but missing from the ABI "
                f"manifest — declare it in nat_api.h and add a NAT_SYM "
                f"row in nat_abi.cpp"))
        for s in sorted(set(symbols) - exports):
            findings.append(Finding(
                "abi", "stale-so", "native/libbrpc_tpu_native.so",
                f"{s} is in the ABI manifest but not exported by the .so "
                f"— rebuild (make -C native)"))

    all_bound: set = set()
    for path in binding_paths:
        b, parse_findings = parse_bindings(path)
        findings.extend(parse_findings)
        rel = os.path.relpath(path, REPO_ROOT)
        all_bound |= set(b.argtypes) | set(b.restype)

        # ---- structs ----
        for name, (lineno, cls) in b.structs.items():
            man = structs.get(name)
            if man is None:
                findings.append(Finding(
                    "abi", "struct-unknown", f"{rel}:{lineno}",
                    f"ctypes mirror {name} has no native counterpart in "
                    f"the manifest"))
                continue
            if ctypes.sizeof(cls) != man["size"]:
                findings.append(Finding(
                    "abi", "struct-layout", f"{rel}:{lineno}",
                    f"sizeof({name}) mismatch: ctypes "
                    f"{ctypes.sizeof(cls)} vs native {man['size']}"))
            pyf = [(fname, getattr(cls, fname).offset,
                    getattr(cls, fname).size, canon(ftype))
                   for fname, ftype in cls._fields_]
            natf = [tuple(row) for row in man["fields"]]
            if len(pyf) != len(natf):
                findings.append(Finding(
                    "abi", "struct-layout", f"{rel}:{lineno}",
                    f"{name}: field count mismatch: ctypes {len(pyf)} vs "
                    f"native {len(natf)}"))
            for (pn, po, ps, pt), (nn, no, ns, nt) in zip(pyf, natf):
                if pn != nn or po != no or ps != ns or \
                        not compatible(pt, nt):
                    findings.append(Finding(
                        "abi", "struct-layout", f"{rel}:{lineno}",
                        f"{name}.{pn}: ctypes (name={pn}, off={po}, "
                        f"size={ps}, {pt}) vs native (name={nn}, off={no},"
                        f" size={ns}, {nt})"))

        # ---- symbols ----
        bound = sorted(set(b.argtypes) | set(b.restype))
        for sym in bound:
            man = symbols.get(sym)
            at_line = b.argtypes.get(sym, (0, None))[0]
            rt_line = b.restype.get(sym, (0, None))[0]
            line = at_line or rt_line
            if man is None:
                findings.append(Finding(
                    "abi", "unknown-symbol", f"{rel}:{line}",
                    f"{sym} is declared in ctypes but is not an exported "
                    f"native symbol"))
                continue
            # restype: ctypes defaults to c_int when never assigned —
            # require an explicit declaration for anything non-void so a
            # u64/ptr return can never be truncated through the default.
            if sym in b.restype:
                py_ret = canon(b.restype[sym][1])
                if not compatible(py_ret, man["ret"]):
                    findings.append(Finding(
                        "abi", "restype-mismatch", f"{rel}:{rt_line}",
                        f"{sym}: restype {py_ret} vs native {man['ret']}"))
            elif man["ret"] not in ("i32", "void"):
                # i32 matches the ctypes default; for void the defaulted
                # c_int reads a dead register, harmless as long as the
                # value is unused — only wider/pointer returns truncate.
                findings.append(Finding(
                    "abi", "missing-restype", f"{rel}:{line}",
                    f"{sym} returns {man['ret']} natively but has no "
                    f"restype (ctypes would truncate through the default "
                    f"c_int)"))
            # argtypes: required whenever the native side takes arguments
            if sym in b.argtypes:
                py_args = [canon(t) for t in b.argtypes[sym][1]]
                nat_args = man["args"]
                if len(py_args) != len(nat_args):
                    findings.append(Finding(
                        "abi", "argcount-mismatch", f"{rel}:{at_line}",
                        f"{sym}: {len(py_args)} argtypes vs native "
                        f"{len(nat_args)} parameters"))
                else:
                    for i, (p, n) in enumerate(zip(py_args, nat_args)):
                        if not compatible(p, n):
                            findings.append(Finding(
                                "abi", "argtype-mismatch",
                                f"{rel}:{at_line}",
                                f"{sym}: arg {i} is {p} in ctypes but "
                                f"{n} natively"))
            elif man["args"]:
                findings.append(Finding(
                    "abi", "missing-argtypes", f"{rel}:{line}",
                    f"{sym} takes {len(man['args'])} native parameters "
                    f"but declares no argtypes (every call is unchecked)"))

    # exports with no ctypes declaration anywhere: a Python caller would
    # reach them through CDLL's attribute fallback (default c_int restype,
    # unchecked args) — require either a declaration or an UNBOUND_OK
    # entry saying the symbol is native-harness-only.
    for sym in sorted(set(symbols) - all_bound - UNBOUND_OK):
        findings.append(Finding(
            "abi", "unbound-symbol", "brpc_tpu/native/__init__.py",
            f"{sym} is exported but has no ctypes argtypes/restype "
            f"declaration — declare it (or add to abi.UNBOUND_OK if it "
            f"is consumed only through nat_api.h)"))
    return findings


def run(binding_paths: Optional[List[str]] = None,
        native_dir: str = NATIVE_DIR) -> List[Finding]:
    """Build manifest + .so, then cross-check. Raises on build failure."""
    manifest = build_manifest(native_dir)
    subprocess.run(["make", "-C", native_dir, "libbrpc_tpu_native.so"],
                   check=True, capture_output=True, timeout=600)
    exports = so_exports(os.path.join(native_dir, "libbrpc_tpu_native.so"))
    return check_abi(manifest, binding_paths or DEFAULT_BINDINGS, exports)
