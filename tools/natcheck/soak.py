"""Sanitizer soak — the full native test matrix under instrumentation
(``tools/check.sh --soak``; VERDICT next-round item 10).

Three legs, each a Finding on failure:

1. ASan+UBSan C smoke in soak mode (``NAT_SOAK=1 nat_smoke_asan``):
   echo sync/async, client bench lanes, native http, h2/gRPC client +
   server, redis store, shm descriptor rings under concurrent drain,
   stats, clean exit.
2. TSan C smoke in the same soak mode.
3. ASan python matrix: the full pytest native suite (client lanes, h2,
   redis, ssl, shm workers — including the TLS lane, which needs
   Python's ssl client) against ``libbrpc_tpu_native_asan.so`` via
   ``BRPC_TPU_NATIVE_SO`` + an LD_PRELOADed libasan. Leak checking is
   disabled for this leg (CPython's interned objects would drown it);
   the C smoke leg keeps LSan on.

The TSan python matrix is deliberately NOT run: preloading libtsan into
an uninstrumented CPython fabricates reports (unintercepted early
allocations); TSan coverage of the shm/h2/redis lanes comes from leg 2.

The combined log is written to ``native/SOAK.md`` — commit it clean.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import List, Tuple

from tools.natcheck import Finding, REPO_ROOT
from tools.natcheck import san

NATIVE_DIR = os.path.join(REPO_ROOT, "native")
SOAK_MD = os.path.join(NATIVE_DIR, "SOAK.md")

# the native-lane pytest matrix (slow sanitizer tests excluded: they
# would recursively build sanitizer lanes)
PYTEST_MATRIX = [
    "tests/test_native.py", "tests/test_native_rpc.py",
    "tests/test_native_client.py", "tests/test_native_http.py",
    "tests/test_native_h2.py", "tests/test_native_redis.py",
    "tests/test_native_ssl.py", "tests/test_native_streaming.py",
    "tests/test_native_stats.py", "tests/test_shm_workers.py",
    "tests/test_shm_desc_ring.py", "tests/test_shm_worker_crash.py",
]


def _libasan_path() -> str:
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"], capture_output=True,
            check=True, timeout=30).stdout.decode().strip()
        return out if os.path.sep in out else ""
    except Exception:
        return ""


def _smoke_leg(kind: str) -> Tuple[List[Finding], str]:
    findings: List[Finding] = []
    env_extra = {"NAT_SOAK": "1"}
    try:
        rc, out = _run_smoke(kind, env_extra)
    except subprocess.CalledProcessError as e:
        findings.append(Finding(
            "soak", f"{kind}-build", "native/Makefile",
            "build failed: " +
            (e.stderr or b"").decode(errors="replace")[-800:]))
        return findings, f"{kind} smoke: BUILD FAILED"
    except subprocess.TimeoutExpired:
        # a hung sanitizer smoke IS the defect class this hunts: record
        # it as a finding instead of losing the whole soak log
        findings.append(Finding(
            "soak", f"{kind}-hang", f"native/nat_smoke_{kind}",
            "soak smoke timed out (hang/deadlock?)"))
        return findings, f"{kind} smoke: TIMED OUT"
    bad = [ln for ln in out.splitlines()
           if any(mk in ln for mk in san._BAD_MARKERS)]
    if rc != 0 or bad:
        head = "; ".join(bad[:3]) if bad else out.strip()[-400:]
        findings.append(Finding(
            "soak", kind, f"native/nat_smoke_{kind}",
            f"soak smoke exited rc={rc}: {head}"))
    return findings, out


def _run_smoke(kind: str, env_extra: dict) -> Tuple[int, str]:
    subprocess.run(["make", "-C", NATIVE_DIR, kind], check=True,
                   capture_output=True, timeout=900)
    env = san._env(kind)
    env.update(env_extra)
    proc = subprocess.run(
        [os.path.join(NATIVE_DIR, f"nat_smoke_{kind}")],
        capture_output=True, timeout=900, env=env)
    return proc.returncode, proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")


def _pytest_leg() -> Tuple[List[Finding], str]:
    findings: List[Finding] = []
    libasan = _libasan_path()
    if not libasan:
        return [Finding("soak", "asan-pytest", "tools/natcheck/soak.py",
                        "libasan.so not found (g++ -print-file-name)")], \
            "asan pytest: libasan unavailable"
    env = dict(os.environ)
    env["LD_PRELOAD"] = libasan
    env["BRPC_TPU_NATIVE_SO"] = os.path.join(
        NATIVE_DIR, "libbrpc_tpu_native_asan.so")
    # leaks: CPython is not leak-clean and the runtime's deliberate
    # process-lifetime leaks (scheduler, stack pool) are design — the C
    # smoke leg runs LSan with the curated suppression file instead
    env["ASAN_OPTIONS"] = "detect_leaks=0:abort_on_error=0:exitcode=87"
    # perf/RSS gates in the matrix detect this and loosen or skip:
    # instrumentation overhead is not a regression
    env["BRPC_TPU_SANITIZED"] = "1"
    env["UBSAN_OPTIONS"] = "print_stacktrace=1"
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", *PYTEST_MATRIX, "-q", "-m",
             "not slow", "-p", "no:cacheprovider"],
            capture_output=True, timeout=1800, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("soak", "asan-pytest-hang", "tests/",
                        "asan python matrix timed out")], \
            "asan pytest: TIMED OUT"
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    san_bad = [ln for ln in out.splitlines()
               if any(mk in ln for mk in san._BAD_MARKERS)]
    if proc.returncode != 0 or san_bad:
        head = "; ".join(san_bad[:3]) if san_bad else \
            out.strip().splitlines()[-1] if out.strip() else "?"
        findings.append(Finding(
            "soak", "asan-pytest", "tests/",
            f"asan python matrix rc={proc.returncode}: {head}"))
    return findings, out


def run(write_log: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    sections = []
    t0 = time.time()
    for kind in ("asan", "tsan"):
        got, out = _smoke_leg(kind)
        findings.extend(got)
        sections.append((f"{kind} C smoke (NAT_SOAK=1)", out))
    got, out = _pytest_leg()
    findings.extend(got)
    sections.append(("asan python native matrix", out))

    if write_log:
        with open(SOAK_MD, "w", encoding="utf-8") as f:
            f.write("# native sanitizer soak log\n\n")
            f.write("Produced by `tools/check.sh --soak` "
                    "(tools/natcheck/soak.py). Three legs: ASan+UBSan C\n"
                    "smoke in soak mode (all lanes incl. h2/gRPC), TSan "
                    "C smoke in soak mode, and the\nfull pytest native "
                    "matrix (client lanes, h2, redis, ssl, shm workers) "
                    "against the\nASan library via BRPC_TPU_NATIVE_SO + "
                    "LD_PRELOAD. See soak.py for why the TSan\npython "
                    "leg is intentionally absent.\n\n")
            f.write("Result: %s (%d finding(s), %.0fs)\n\n" %
                    ("CLEAN" if not findings else "FAILING",
                     len(findings), time.time() - t0))
            for f2 in findings:
                f.write("- FINDING: %s\n" % f2)
            for title, body in sections:
                tail = "\n".join(body.strip().splitlines()[-25:])
                f.write("\n## %s\n\n```\n%s\n```\n" % (title, tail))
    return findings
