"""natcheck — standing correctness tooling for the native runtime.

Six passes over the C++ core and its FFI boundary (see README.md here):

- ``abi``  — cross-checks the compiler-generated ABI manifest
  (native/nat_abi, built from nat_api.h) against the ctypes declarations
  in brpc_tpu/native/__init__.py and against ``nm -D`` of the built .so.
- ``lint`` — regex/clang-agnostic concurrency lint over native/src/
  enforcing repo invariants (explicit memory_order, no racy exit-time
  statics in thread-spawning files, seqlock readers re-check).
- ``lockorder`` — lock-rank verification: every mutex carries a declared
  rank (NatMutex<kLockRank...> / natcheck:rank comments); the static
  acquires-while-holding graph must be rank-monotone and no lock may be
  held across a fiber-switch/blocking point. Runtime twin: the
  NAT_LOCKRANK build (``make -C native lockrank``) asserts the same
  order on a TLS held-rank stack during nat_smoke runs.
- ``refown`` — declared ownership/refcount contracts: every add_ref/
  release goes through the NAT_REF_* macro grammar (nat_refown.h), the
  acquire/release/transfer graph per tag must balance (no unreleased
  acquires, no orphan releases, no early-return leaks, no borrows after
  release), deliberate leaks carry natcheck:leak declarations backing
  native/lsan.supp. Runtime twin: the NAT_REFGUARD build (``make -C
  native refguard``) asserts per-object per-tag balances at runtime.
- ``model`` — dsched deterministic interleaving checker (native/model/):
  exhaustive + seeded-random exploration of the lock-free primitives
  (wsq, descriptor ring, arena, butex protocol, EOWNERDEAD recovery)
  with stale-read weak-memory modeling; replayable failing schedules.
- ``san``  — builds the .so under ASan+UBSan and TSan and runs the native
  smoke driver under each; ``soak`` (tools/check.sh --soak) extends this
  to the full native matrix and logs native/SOAK.md.

Standing check.sh-only lanes: ``--refguard`` (the refown runtime twin
over the C smoke + pytest native matrix, refguard.py), ``--chaos``
(fixed-seed fault-injection soak, chaos.py) and ``--bench`` (the perf
regression gate, benchgate.py:
bench.py + nat_prof profile -> schema'd artifact -> headline-lane diff
against the last committed BENCH_r*.json with tolerance bands).

Entry points: ``python -m tools.natcheck`` or ``make -C native check``
(which delegates to tools/check.sh).
"""
from __future__ import annotations

import dataclasses
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass
class Finding:
    """One checker finding; `where` is file[:line], rule is a short slug."""

    pass_name: str   # "abi" | "lint" | "san"
    rule: str        # e.g. "atomic-order", "struct-layout"
    where: str       # "path" or "path:lineno"
    message: str

    def __str__(self) -> str:
        return f"{self.where}: [{self.pass_name}/{self.rule}] {self.message}"


def print_findings(findings, stream=None) -> int:
    """Print findings one per line; returns the count (0 = clean)."""
    import sys

    stream = stream or sys.stdout
    for f in findings:
        print(str(f), file=stream)
    return len(findings)
