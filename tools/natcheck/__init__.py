"""natcheck — standing correctness tooling for the native runtime.

Three passes over the C++ core and its FFI boundary (see README.md here):

- ``abi``  — cross-checks the compiler-generated ABI manifest
  (native/nat_abi, built from nat_api.h) against the ctypes declarations
  in brpc_tpu/native/__init__.py and against ``nm -D`` of the built .so.
- ``lint`` — regex/clang-agnostic concurrency lint over native/src/
  enforcing repo invariants (explicit memory_order, no racy exit-time
  statics in thread-spawning files, seqlock readers re-check).
- ``san``  — builds the .so under ASan+UBSan and TSan and runs the native
  smoke driver (echo, http, stats, clean exit) under each.

Entry points: ``python -m tools.natcheck`` or ``make -C native check``
(which delegates to tools/check.sh).
"""
from __future__ import annotations

import dataclasses
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass
class Finding:
    """One checker finding; `where` is file[:line], rule is a short slug."""

    pass_name: str   # "abi" | "lint" | "san"
    rule: str        # e.g. "atomic-order", "struct-layout"
    where: str       # "path" or "path:lineno"
    message: str

    def __str__(self) -> str:
        return f"{self.where}: [{self.pass_name}/{self.rule}] {self.message}"


def print_findings(findings, stream=None) -> int:
    """Print findings one per line; returns the count (0 = clean)."""
    import sys

    stream = stream or sys.stdout
    for f in findings:
        print(str(f), file=stream)
    return len(findings)
