"""Model pass — build + run the dsched deterministic interleaving
checker (native/model/) over the lock-free primitives.

``make -C native nat_model`` compiles wsq.h + nat_desc_ring.h against
the dsched virtual-thread shim (-DNAT_MODEL=1, src/nat_atomic.h seam)
and ``nat_model --smoke`` explores every scenario (wstack, wsq, ring,
arena, butex, recovery-vs-offer, quiesce, refrace, refxfer)
exhaustively under a preemption bound plus
seeded random walks. Deterministic: same seed => same trace => same
hash, and a failing schedule prints a replayable seed / choice string.

The pass fails on any FAIL line or nonzero exit; build failures are
raised (natcheck reports the pass as broken, exit 2).
"""
from __future__ import annotations

import os
import subprocess
from typing import List, Tuple

from tools.natcheck import Finding, REPO_ROOT

NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def build_and_run(args=("--smoke",), timeout: int = 900,
                  model_inc: str = "") -> Tuple[int, str]:
    """Build nat_model (optionally with MODEL_INC include overrides so a
    doctored header can shadow a shipped one — the golden tests' seam)
    and run it. Returns (exit code, combined output)."""
    make_cmd = ["make", "-C", NATIVE_DIR, "nat_model"]
    if model_inc:
        # force a rebuild: the include override changes what's compiled
        make_cmd += [f"MODEL_INC={model_inc}", "-B"]
    subprocess.run(make_cmd, check=True, capture_output=True,
                   timeout=timeout)
    proc = subprocess.run(
        [os.path.join(NATIVE_DIR, "nat_model"), *args],
        capture_output=True, timeout=timeout)
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    return proc.returncode, out


def run() -> List[Finding]:
    findings: List[Finding] = []
    try:
        rc, out = build_and_run()
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            "nat_model build failed: " +
            (e.stderr or b"").decode(errors="replace")[-800:])
    for line in out.splitlines():
        if "FAIL" in line:
            findings.append(Finding(
                "model", "interleaving", "native/nat_model",
                line.strip()))
    if rc != 0 and not findings:
        findings.append(Finding(
            "model", "interleaving", "native/nat_model",
            f"nat_model exited rc={rc}: {out.strip()[-400:]}"))
    return findings
