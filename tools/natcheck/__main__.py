"""CLI: ``python -m tools.natcheck [abi] [lint] [lockorder] [refown] [wiretrust] [san] [model]``.

With no pass named, runs the fast static passes (lint + abi + lockorder
+ refown + wiretrust).
``--model`` (or naming ``model``) adds the dsched interleaving smoke
(compiles native/model/, bounded exploration); ``san`` (or
NATCHECK_SLOW=1 in tools/check.sh) adds the sanitizer lane. Exits 1 on
any finding, 2 when a pass could not run at all.
"""
from __future__ import annotations

import argparse
import os
import sys

# allow `python tools/natcheck` too, not just -m from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.natcheck import print_findings  # noqa: E402

DEFAULT_PASSES = ["lint", "abi", "lockorder", "refown", "wiretrust"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools.natcheck")
    ap.add_argument("passes", nargs="*",
                    choices=["abi", "lint", "lockorder", "refown", "wiretrust",
                             "san", "model", []],
                    help="passes to run (default: lint abi lockorder refown wiretrust)")
    ap.add_argument("--model", action="store_true",
                    help="also run the dsched interleaving smoke")
    args = ap.parse_args(argv)
    passes = args.passes or list(DEFAULT_PASSES)
    if args.model and "model" not in passes:
        passes.append("model")

    findings = []
    broken = False
    for p in passes:
        try:
            if p == "lint":
                from tools.natcheck import lint
                got = lint.run()
            elif p == "abi":
                from tools.natcheck import abi
                got = abi.run()
            elif p == "lockorder":
                from tools.natcheck import lockorder
                got = lockorder.run()
            elif p == "refown":
                from tools.natcheck import refown
                got = refown.run()
            elif p == "wiretrust":
                from tools.natcheck import wiretrust
                got = wiretrust.run()
            elif p == "model":
                from tools.natcheck import model
                got = model.run()
            else:
                from tools.natcheck import san
                got = san.run()
        except Exception as e:  # toolchain missing, build failure, ...
            print(f"natcheck: {p} pass could not run: {e}", file=sys.stderr)
            broken = True
            continue
        findings.extend(got)
        print(f"natcheck: {p}: "
              f"{'clean' if not got else f'{len(got)} finding(s)'}")
    n = print_findings(findings)
    if broken:
        return 2
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main())
