"""refguard lane — the refown runtime twin over the real workload
(``tools/check.sh --refguard``).

Three legs, each a Finding on failure:

1. C smoke against the ``-DNAT_REFGUARD`` build (``make -C native
   refguard`` + ``nat_smoke_refguard``): every NAT_REF_* site feeds the
   per-object per-tag balance ledger; an unbalanced pair, a
   release-after-final or a borrow of an invalidated object aborts with
   the failing tag pair printed.
2. The deliberately-broken scenario (``NAT_REFGUARD_BREAK=1``): the
   guard MUST abort on the seeded double release — a validator that
   cannot fire is indistinguishable from one that works.
3. The pytest native matrix against the refguard .so via the
   ``BRPC_TPU_NATIVE_SO`` loader override — the full Python-driven
   socket/channel/shm/h2/redis churn with the ledger live.
"""
from __future__ import annotations

import os
import subprocess
import sys
from typing import List, Tuple

from tools.natcheck import Finding, REPO_ROOT
from tools.natcheck.soak import PYTEST_MATRIX

NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def _build() -> None:
    subprocess.run(["make", "-C", NATIVE_DIR, "refguard"], check=True,
                   capture_output=True, timeout=900)


def _smoke_leg() -> List[Finding]:
    smoke = os.path.join(NATIVE_DIR, "nat_smoke_refguard")
    try:
        proc = subprocess.run([smoke], capture_output=True, timeout=600)
    except subprocess.TimeoutExpired:
        return [Finding("refguard", "smoke-hang", "native/nat_smoke_refguard",
                        "refguard smoke timed out (hang/deadlock?)")]
    if proc.returncode != 0:
        tail = (proc.stdout + proc.stderr).decode(
            errors="replace").strip()[-500:]
        return [Finding(
            "refguard", "smoke", "native/nat_smoke_refguard",
            f"refguard smoke exited rc={proc.returncode}: {tail}")]
    return []


def _break_leg() -> List[Finding]:
    smoke = os.path.join(NATIVE_DIR, "nat_smoke_refguard")
    env = dict(os.environ)
    env["NAT_REFGUARD_BREAK"] = "1"
    try:
        proc = subprocess.run([smoke], capture_output=True, timeout=120,
                              env=env)
    except subprocess.TimeoutExpired:
        return [Finding("refguard", "break-hang",
                        "native/nat_smoke_refguard",
                        "break scenario timed out")]
    err = proc.stderr.decode(errors="replace")
    if proc.returncode == 0 or "nat_refguard:" not in err:
        return [Finding(
            "refguard", "break-silent", "native/nat_smoke_refguard",
            f"the seeded double release did NOT trip the guard "
            f"(rc={proc.returncode}) — a validator that cannot fire is "
            f"indistinguishable from one that works")]
    return []


def _pytest_leg() -> Tuple[List[Finding], str]:
    env = dict(os.environ)
    env["BRPC_TPU_NATIVE_SO"] = os.path.join(
        NATIVE_DIR, "libbrpc_tpu_native_refguard.so")
    # the ledger serializes every ref op through its shard lock: perf/RSS
    # gates in the matrix detect this and loosen or skip
    env["BRPC_TPU_SANITIZED"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", *PYTEST_MATRIX, "-q", "-m",
             "not slow", "-p", "no:cacheprovider"],
            capture_output=True, timeout=1800, env=env, cwd=REPO_ROOT)
    except subprocess.TimeoutExpired:
        return [Finding("refguard", "pytest-hang", "tests/",
                        "refguard python matrix timed out")], ""
    out = proc.stdout.decode(errors="replace") + \
        proc.stderr.decode(errors="replace")
    if proc.returncode != 0:
        tail = "\n".join(out.strip().splitlines()[-12:])
        return [Finding(
            "refguard", "pytest", "tests/",
            f"pytest native matrix under the refguard .so exited "
            f"rc={proc.returncode}:\n{tail}")], out
    return [], out


def run() -> List[Finding]:
    try:
        _build()
    except subprocess.CalledProcessError as e:
        return [Finding(
            "refguard", "build", "native/Makefile",
            "refguard build failed: " +
            (e.stderr or b"").decode(errors="replace")[-800:])]
    except subprocess.TimeoutExpired:
        return [Finding("refguard", "build-hang", "native/Makefile",
                        "refguard build timed out")]
    findings = _smoke_leg()
    findings += _break_leg()
    got, _ = _pytest_leg()
    findings += got
    return findings
