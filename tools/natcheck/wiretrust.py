"""Wire-input taint verification — hostile bytes must meet a bound.

The native tree turns attacker- or corruption-controlled bytes into
lengths, offsets, allocation sizes and loop bounds in six hand-rolled
parsers (tpu_std rpc_meta varints, HTTP/1, h2/HPACK, RESP, the recordio
capture loader, the shm descriptor/fabric records). This pass makes the
trust boundary explicit and machine-checked:

Annotation surface (``native/src/nat_internal.h``):

- ``NAT_WIRE(expr)`` — a no-op macro marking ``expr`` as wire-origin at
  the point it enters the parser. On an assignment/declaration line the
  declared variable becomes tainted; standalone, every identifier inside
  the parens does.
- ``// natcheck:wire: a, b`` — names identifiers (locals or parameters)
  of the enclosing function as wire-tainted from that line on. On or
  directly above a function signature it taints the named parameters.

Taint propagates forward through assignments (including through calls:
``n = rd_be32(p)`` with ``p`` tainted taints ``n``) and — with a
transitive call closure reusing lockorder.py's walker — through function
parameters and return values. A value stops being dangerous once a
DOMINATING BOUNDS CHECK is seen: a relational comparison against it, or
a ``min``/``max``/``clamp`` rebind, or a masking/modulo derivation.

Rules (suppress with ``// natcheck:allow(wiretrust): why``):

- ``wire-int-unbounded``: a wire-derived integer used as a
  memcpy/memmove/memset length, an array index, or a pointer offset
  with no dominating bounds check.
- ``wire-alloc-unclamped``: a wire-derived integer used as an
  allocation size (malloc/calloc/realloc/new[]) or container
  resize/reserve with no clamp.
- ``wire-loop-unbounded``: a loop whose bound is a wire-derived integer
  with no prior cap (the loop's own condition does not count — that IS
  the unbounded iteration).

Interprocedural findings are reported at the call site ("via helper()"),
so the fix lands where the unclamped value crosses the boundary.
"""
from __future__ import annotations

import os
import re
import sys
from functools import lru_cache
from typing import Dict, List, Optional, Set, Tuple

if __package__ in (None, ""):  # `python tools/natcheck/wiretrust.py`
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))

from tools.natcheck import Finding, REPO_ROOT  # noqa: E402
from tools.natcheck.lockorder import (  # noqa: E402
    _CALL, _CALL_STOP, _allowed, _strip_comments_and_strings,
    collect_sources, parse_functions, FuncInfo, _dedupe)

SRC_DIR = os.path.join(REPO_ROOT, "native", "src")

# names run until a dash/paren/end: "natcheck:wire: a, b — why"
_WIRE_COMMENT = re.compile(r"natcheck:wire\s*[:(]\s*([A-Za-z_]\w*"
                           r"(?:\s*,\s*[A-Za-z_]\w*)*)")
_WIRE_MACRO = re.compile(r"\bNAT_WIRE\s*\(")

# relational operator that is a COMPARISON (not <<, >>, ->, <>, template)
_CMP = r"(?:==|!=|<=|>=|(?<![<-])<(?![<=])|(?<![->])>(?![>=]))"

# assignment line: `lhs = rhs` / `type lhs = rhs` / `lhs += rhs`
_ASSIGN = re.compile(
    r"(?:^|[;{(]|\bif\b|\bwhile\b)\s*"               # statement start-ish
    r"(?:[\w:<>,*&\s]+?\s)?"                          # optional decl type
    r"([A-Za-z_]\w*)\s*"                              # lhs identifier
    r"(?:\[[^\]]*\]\s*)?"                             # optional subscript
    r"(\+=|-=|\|=|&=|\^=|=)(?!=)\s*(.*)")             # op + rhs
_RETURN = re.compile(r"\breturn\b\s*([^;]*);")

# sinks
_MEMLEN = re.compile(r"\b(?:memcpy|memmove|memset)\s*\(")
_ALLOC = re.compile(
    r"(?:\.|->)\s*(?:resize|reserve)\s*\(|"
    r"\b(?:malloc|alloca)\s*\(|\bcalloc\s*\(|\brealloc\s*\(|"
    r"\bnew\s+[\w:<>]+\s*\[")
_NEW_ARR = re.compile(r"\bnew\s+[\w:<>]+\s*\[([^\]]*)\]")
_INDEX = re.compile(r"\b[A-Za-z_]\w*(?:\.|->)?\w*\s*\[([^\]]+)\]")
_PTR_OFF = re.compile(r"\*\s*\(\s*[A-Za-z_][\w.>\-]*\s*\+\s*([^)]+)\)")
_FOR_COND = re.compile(r"\bfor\s*\([^;]*;([^;]*);")
_WHILE_COND = re.compile(r"\bwhile\s*\(([^)]*)\)")

# call-name stoplist for the call closure: lockorder's plus the sink
# names and libc converters this pass models directly
_STOP = _CALL_STOP | {
    "memmove", "realloc", "alloca", "strtol", "strtoll", "strtoul",
    "strtoull", "memchr", "copy_to", "fetch", "pop_front", "length",
    "NAT_WIRE", "if", "return", "sizeof",
}

_SANITIZED = re.compile(
    r"\b(?:std::)?(?:min|max|clamp)\s*\(|"
    r"%(?!=)|"                                 # modulo derivation
    r"&\s*(?:0[xX][0-9a-fA-F]+|\d+|k[A-Z]\w*)")  # constant mask


@lru_cache(maxsize=None)
def _ident_re(name: str) -> "re.Pattern":
    return re.compile(r"\b%s\b" % re.escape(name))


@lru_cache(maxsize=None)
def _cmp_res(ident: str) -> Tuple["re.Pattern", ...]:
    e = re.escape(ident)
    return (re.compile(r"\b%s\b\s*%s" % (e, _CMP)),
            re.compile(r"%s[^;,={}()]{0,60}\b%s\b" % (_CMP, e)),
            re.compile(r"\b(?:std::)?(?:min|max|clamp)\s*"
                       r"\([^;{}]{0,120}\b%s\b" % e))


@lru_cache(maxsize=None)
def _loop_bound_re(ident: str) -> "re.Pattern":
    e = re.escape(ident)
    return re.compile(r"\b%s\b\s*(?:%s|--)|%s\s*[^=\s]*\s*\b%s\b"
                      % (e, _CMP, _CMP, e))


def _has_cmp_against(text: str, ident: str) -> bool:
    """`ident` appears on either side of a relational comparison."""
    if ident not in text:
        return False
    return any(r.search(text) for r in _cmp_res(ident))


def _call_args(text: str, open_idx: int) -> List[str]:
    """Split the argument list whose '(' is at `open_idx` on top-level
    commas. Returns [] on unbalanced text."""
    depth = 0
    args: List[str] = []
    cur = []
    k = open_idx
    while k < len(text):
        ch = text[k]
        if ch in "([":
            depth += 1
            if depth > 1:
                cur.append(ch)
        elif ch in ")]":
            depth -= 1
            if depth == 0:
                args.append("".join(cur).strip())
                return args
            cur.append(ch)
        elif ch == "," and depth == 1:
            args.append("".join(cur).strip())
            cur = []
        else:
            if depth >= 1:
                cur.append(ch)
        k += 1
    return []


def _param_names(scrubbed: str, fn: FuncInfo) -> List[str]:
    """Parameter names of `fn`, by position, parsed from the signature
    directly before the body's opening brace."""
    j = fn.body_off - 1
    # skip const/noexcept/trailing-return between ')' and '{'
    while j >= 0 and scrubbed[j] != ")":
        j -= 1
    if j < 0:
        return []
    depth = 0
    k = j
    while k >= 0:
        if scrubbed[k] == ")":
            depth += 1
        elif scrubbed[k] == "(":
            depth -= 1
            if depth == 0:
                break
        k -= 1
    if k < 0:
        return []
    params = _call_args(scrubbed, k)
    names: List[str] = []
    for p in params:
        p = p.split("=")[0].strip()       # drop default value
        p = re.sub(r"\[[^\]]*\]\s*$", "", p)  # drop array suffix
        m = re.search(r"([A-Za-z_]\w*)\s*$", p)
        if m and m.group(1) not in ("void", "const", "int", "char",
                                    "size_t", "uint64_t", "uint32_t"):
            names.append(m.group(1))
        else:
            names.append("")              # unnamed / type-only param
    if names == [""]:
        return []
    return names


class Summary:
    """Per-function interprocedural facts, computed to fixpoint."""

    def __init__(self):
        # param index -> rule it reaches unchecked ("wire-int-unbounded"
        # / "wire-alloc-unclamped" / "wire-loop-unbounded")
        self.sink_params: Dict[int, str] = {}
        self.returns_wire = False
        self.returns_params: Set[int] = set()


class _Analysis:
    """One pass over one function body: propagate labels line by line.

    Labels: "wire" (a real wire source) and "p<i>" (came from parameter
    i — used only to build the interprocedural summary)."""

    def __init__(self, fn: FuncInfo, rel: str, raw_lines: List[str],
                 params: List[str],
                 summaries: Dict[str, Summary]):
        self.fn = fn
        self.rel = rel
        self.raw_lines = raw_lines
        self.params = params
        self.summaries = summaries
        self.labels: Dict[str, Set[str]] = {}
        self.checked: Set[str] = set()
        self.findings: List[Finding] = []
        self.summary = Summary()

    # -- label helpers ------------------------------------------------------

    def _live_labels(self, expr: str) -> Set[str]:
        """Labels of every tainted-and-unchecked ident in `expr`."""
        out: Set[str] = set()
        for ident, labs in self.labels.items():
            if ident in self.checked or ident not in expr:
                continue
            if _ident_re(ident).search(expr):
                out |= labs
        return out

    def _taint(self, ident: str, labs: Set[str]) -> None:
        if not labs or not ident:
            return
        self.labels.setdefault(ident, set()).update(labs)
        self.checked.discard(ident)  # fresh wire value: re-check needed

    def _allowed_at(self, abs_line: int) -> bool:
        i = abs_line - 1
        return (_allowed(self.raw_lines, i, "wiretrust") or
                _allowed(self.raw_lines, i, "wire-int-unbounded") or
                _allowed(self.raw_lines, i, "wire-alloc-unclamped") or
                _allowed(self.raw_lines, i, "wire-loop-unbounded"))

    def _report(self, rule: str, abs_line: int, msg: str) -> None:
        if self._allowed_at(abs_line):
            return
        self.findings.append(Finding(
            "wiretrust", rule, f"{self.rel}:{abs_line}", msg))

    def _sink(self, rule: str, labs: Set[str], abs_line: int,
              what: str, report: bool) -> None:
        if "wire" in labs and report:
            self._report(rule, abs_line,
                         f"wire-derived integer used as {what} with no "
                         f"dominating bounds check")
        for lab in labs:
            if lab.startswith("p"):
                idx = int(lab[1:])
                self.summary.sink_params.setdefault(idx, rule)

    # -- seeds --------------------------------------------------------------

    def _seed_line(self, line: str, raw: str, abs_line: int) -> None:
        m = _WIRE_COMMENT.search(raw)
        if m:
            for name in re.split(r"[,\s]+", m.group(1)):
                if name:
                    self._taint(name, {"wire"})
        if _WIRE_MACRO.search(line):
            am = re.search(r"([A-Za-z_]\w*)\s*=[^=].*\bNAT_WIRE\s*\(",
                           line)
            if am:
                self._taint(am.group(1), {"wire"})
            else:
                mm = _WIRE_MACRO.search(line)
                args = _call_args(line, mm.end() - 1)
                for a in args:
                    for ident in re.findall(r"[A-Za-z_]\w*", a):
                        self._taint(ident, {"wire"})

    def _seed_params(self) -> None:
        # natcheck:wire above the signature taints named params; every
        # param additionally carries its positional label for summaries
        for off, name in enumerate(self.params):
            if name:
                self._taint(name, {"p%d" % off})
        j = self.fn.start_line - 2
        while j >= 0 and self.fn.start_line - j <= 6:
            stripped = self.raw_lines[j].strip() \
                if j < len(self.raw_lines) else ""
            if not stripped.startswith("//"):
                break
            m = _WIRE_COMMENT.search(stripped)
            if m:
                for name in re.split(r"[,\s]+", m.group(1)):
                    if name:
                        self._taint(name, {"wire"})
            j -= 1

    # -- the walk -----------------------------------------------------------

    def run(self, report: bool) -> None:
        body_lines = self.fn.body.split("\n")
        self._seed_params()
        # callees whose return value is wire-tainted can introduce taint
        # on lines that mention no currently-live ident
        wire_returners = tuple(n for n, s in self.summaries.items()
                               if s.returns_wire)
        # two passes: the first discovers taint introduced later in the
        # body by helpers whose summaries mention it; the second reports
        # with the full taint map. Only the last pass reports.
        for final in (False, True):
            self.checked = set()
            for idx, line in enumerate(body_lines):
                abs_line = self.fn.start_line + idx
                raw = self.raw_lines[abs_line - 1] \
                    if abs_line - 1 < len(self.raw_lines) else ""
                # fast path: a line with no live tainted ident, no wire
                # annotation, and no taint-returning callee cannot
                # change state or fire a rule
                if "NAT_WIRE" not in line and "natcheck:wire" not in raw:
                    live = any(i in line for i in self.labels
                               if i not in self.checked)
                    if not live and not any(n in line
                                            for n in wire_returners):
                        continue
                self._seed_line(line, raw, abs_line)
                self._loops(line, abs_line, report and final)
                self._checks(line)
                self._assign(line)
                self._calls(line, abs_line, report and final)
                self._sinks(line, abs_line, report and final)
                self._returns(line)

    def _loops(self, line: str, abs_line: int, report: bool) -> None:
        conds = [m.group(1) for m in _FOR_COND.finditer(line)]
        conds += [m.group(1) for m in _WHILE_COND.finditer(line)]
        for cond in conds:
            labs: Set[str] = set()
            for ident, ls in self.labels.items():
                if ident in self.checked or ident not in cond:
                    continue
                if _loop_bound_re(ident).search(cond):
                    labs |= ls
            if labs:
                self._sink("wire-loop-unbounded", labs, abs_line,
                           "a loop bound", report)

    def _checks(self, line: str) -> None:
        # loop conditions must not count as the bound for the loop rule,
        # but DO dominate sinks inside the loop body (i < n caps i); the
        # simple approximation: any relational mention checks the ident.
        for ident in list(self.labels):
            if ident in self.checked or ident not in line:
                continue
            if _has_cmp_against(line, ident):
                self.checked.add(ident)

    def _assign(self, line: str) -> None:
        for m in _ASSIGN.finditer(line):
            lhs, op, rhs = m.group(1), m.group(2), m.group(3)
            if lhs in ("if", "while", "return", "for", "else"):
                continue
            if _SANITIZED.search(rhs):
                continue  # min/clamp/mask/mod: bounded by construction
            labs = self._live_labels(rhs)
            # returns-taint through a call on the RHS
            for cm in _CALL.finditer(rhs):
                s = self.summaries.get(cm.group(1))
                if s is None:
                    continue
                if s.returns_wire:
                    labs = labs | {"wire"}
                if s.returns_params:
                    args = _call_args(rhs, cm.end() - 1)
                    for pi in s.returns_params:
                        if pi < len(args):
                            labs = labs | self._live_labels(args[pi])
            if op == "=" and not labs:
                # overwritten with an untainted value: clears taint
                if lhs in self.labels and not \
                        _ident_re(lhs).search(rhs):
                    self.labels.pop(lhs, None)
                    self.checked.discard(lhs)
                continue
            self._taint(lhs, labs)

    def _calls(self, line: str, abs_line: int, report: bool) -> None:
        for m in _CALL.finditer(line):
            name = m.group(1)
            if name in _STOP:
                continue
            s = self.summaries.get(name)
            if s is None or not s.sink_params:
                continue
            args = _call_args(line, m.end() - 1)
            for pi, rule in s.sink_params.items():
                if pi >= len(args):
                    continue
                labs = self._live_labels(args[pi])
                what = {"wire-int-unbounded": "length/index",
                        "wire-alloc-unclamped": "allocation",
                        "wire-loop-unbounded": "loop-bound"}[rule]
                if "wire" in labs and report:
                    self._report(rule, abs_line,
                                 f"wire-derived integer flows unchecked "
                                 f"into a {what} sink via {name}() "
                                 f"(parameter {pi})")
                for lab in labs:
                    if lab.startswith("p"):
                        self.summary.sink_params.setdefault(
                            int(lab[1:]), rule)

    def _sinks(self, line: str, abs_line: int, report: bool) -> None:
        # memcpy/memmove/memset length (3rd argument)
        for m in _MEMLEN.finditer(line):
            args = _call_args(line, m.end() - 1)
            if len(args) >= 3:
                self._sink("wire-int-unbounded",
                           self._live_labels(args[2]), abs_line,
                           "a memcpy/memmove/memset length", report)
        # allocation / resize / reserve
        for m in _ALLOC.finditer(line):
            nm = _NEW_ARR.search(line, m.start())
            if nm is not None and nm.start() == m.start():
                expr = nm.group(1)
            else:
                op = line.find("(", m.start())
                if op < 0:
                    continue
                args = _call_args(line, op)
                expr = ",".join(args)
            self._sink("wire-alloc-unclamped", self._live_labels(expr),
                       abs_line, "an allocation size", report)
        # array index / pointer offset
        for m in _INDEX.finditer(line):
            self._sink("wire-int-unbounded",
                       self._live_labels(m.group(1)), abs_line,
                       "an array index", report)
        for m in _PTR_OFF.finditer(line):
            self._sink("wire-int-unbounded",
                       self._live_labels(m.group(1)), abs_line,
                       "a pointer offset", report)

    def _returns(self, line: str) -> None:
        for m in _RETURN.finditer(line):
            labs = self._live_labels(m.group(1))
            if "wire" in labs:
                self.summary.returns_wire = True
            for lab in labs:
                if lab.startswith("p"):
                    self.summary.returns_params.add(int(lab[1:]))


def collect_wire_sources(src_dir: str = SRC_DIR) \
        -> List[Tuple[str, int, str]]:
    """Every annotated wire source: (relpath, line, annotation text).
    The golden breadth-floor test counts these."""
    out: List[Tuple[str, int, str]] = []
    for path, text in collect_sources(src_dir).items():
        rel = os.path.relpath(path, REPO_ROOT)
        for i, raw in enumerate(text.splitlines()):
            if "#define NAT_WIRE" in raw:
                continue  # the macro definition is not a source
            if _WIRE_COMMENT.search(raw) or \
                    _WIRE_MACRO.search(_strip_comments_and_strings(raw)):
                out.append((rel, i + 1, raw.strip()))
    return out


def check(src_dir: str = SRC_DIR, dump: bool = False) -> List[Finding]:
    sources = collect_sources(src_dir)
    per_fn: List[Tuple[FuncInfo, str, List[str], List[str]]] = []
    summaries: Dict[str, Summary] = {}
    for path, text in sources.items():
        rel = os.path.relpath(path, REPO_ROOT)
        raw_lines = text.splitlines()
        scrubbed = "\n".join(_strip_comments_and_strings(ln)
                             for ln in raw_lines)
        for fn in parse_functions(path, text):
            params = _param_names(scrubbed, fn)
            per_fn.append((fn, rel, raw_lines, params))

    # fixpoint over summaries (3 rounds bounds the transitive closure
    # depth this tree needs); after round one, only functions whose
    # callees' summaries changed are re-analyzed
    dirty = {fn.name for fn, _, _, _ in per_fn}
    for _ in range(3):
        changed_names: Set[str] = set()
        for fn, rel, raw_lines, params in per_fn:
            if fn.name not in dirty:
                continue
            a = _Analysis(fn, rel, raw_lines, params, summaries)
            a.run(report=False)
            prev = summaries.get(fn.name)
            if prev is None or \
                    prev.sink_params != a.summary.sink_params or \
                    prev.returns_wire != a.summary.returns_wire or \
                    prev.returns_params != a.summary.returns_params:
                summaries[fn.name] = a.summary
                changed_names.add(fn.name)
        if not changed_names:
            break
        dirty = {fn.name for fn, _, _, _ in per_fn
                 if any(c in changed_names for c, _ in fn.calls)}

    findings: List[Finding] = []
    for fn, rel, raw_lines, params in per_fn:
        a = _Analysis(fn, rel, raw_lines, params, summaries)
        a.run(report=True)
        findings.extend(a.findings)

    if dump:
        print("== wire sources ==")
        for rel, line, text in collect_wire_sources(src_dir):
            print(f"  {rel}:{line}  {text}")
        print("== interprocedural sink summaries ==")
        for name, s in sorted(summaries.items()):
            if s.sink_params or s.returns_wire:
                print(f"  {name}: params {s.sink_params} "
                      f"returns_wire={s.returns_wire}")
    return _dedupe(findings)


def run(src_dir: str = SRC_DIR) -> List[Finding]:
    return check(src_dir)


if __name__ == "__main__":
    src = SRC_DIR
    dump = "--dump" in sys.argv
    for a in sys.argv[1:]:
        if a != "--dump":
            src = a
    fs = check(src, dump=dump)
    for f in fs:
        print(f)
    sys.exit(1 if fs else 0)
