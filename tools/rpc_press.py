#!/usr/bin/env python
"""rpc_press — protocol-generic load generator.

Counterpart of tools/rpc_press (/root/reference/tools/rpc_press/): fires a
method at a target at a throttled qps (0 = max speed) from JSON bodies,
reporting qps + latency percentiles from a bvar LatencyRecorder.

Usage:
  python tools/rpc_press.py --server 127.0.0.1:8000 \
      --method EchoService.Echo --proto brpc_tpu.rpc.proto.echo_pb2 \
      --request-type EchoRequest --input '{"message": "hi"}' \
      --qps 1000 --duration 10 --threads 4
"""
from __future__ import annotations

import argparse
import importlib
import json
import sys
import threading
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--server", required=True, help="ip:port or list://...")
    ap.add_argument("--lb", default="", help="load balancer when NS url")
    ap.add_argument("--method", required=True, help="Service.Method")
    ap.add_argument("--proto", default="brpc_tpu.rpc.proto.echo_pb2",
                    help="python module holding the message classes")
    ap.add_argument("--request-type", default="EchoRequest")
    ap.add_argument("--response-type", default="")
    ap.add_argument("--input", default="{}",
                    help="JSON body or @file with one JSON per line")
    ap.add_argument("--qps", type=float, default=0, help="0 = no throttle")
    ap.add_argument("--duration", type=float, default=10)
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--timeout-ms", type=float, default=1000)
    ap.add_argument("--protocol", default="tpu_std")
    args = ap.parse_args()

    from brpc_tpu import bvar, rpc
    from brpc_tpu.json2pb import json_to_pb

    mod = importlib.import_module(args.proto)
    req_cls = getattr(mod, args.request_type)
    resp_name = args.response_type or args.request_type.replace(
        "Request", "Response")
    resp_cls = getattr(mod, resp_name)

    if args.input.startswith("@"):
        with open(args.input[1:]) as f:
            bodies = [line.strip() for line in f if line.strip()]
    else:
        bodies = [args.input]
    requests = [json_to_pb(b, req_cls) for b in bodies]

    recorder = bvar.LatencyRecorder()
    sent = bvar.Adder()
    errors_count = bvar.Adder()
    stop = threading.Event()
    interval = args.threads / args.qps if args.qps > 0 else 0

    def worker(idx: int):
        ch = rpc.Channel(rpc.ChannelOptions(
            timeout_ms=args.timeout_ms, protocol=args.protocol))
        if ch.init(args.server, args.lb) != 0:
            print(f"worker {idx}: channel init failed", file=sys.stderr)
            return
        i = 0
        next_fire = time.monotonic()
        while not stop.is_set():
            if interval:
                now = time.monotonic()
                if now < next_fire:
                    time.sleep(min(interval, next_fire - now))
                    continue
                next_fire += interval
            req = requests[i % len(requests)]
            i += 1
            t0 = time.monotonic()
            cntl, _ = ch.call(args.method, req, resp_cls)
            sent.update(1)
            if cntl.failed():
                errors_count.update(1)
            else:
                recorder.update((time.monotonic() - t0) * 1e6)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(args.threads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    try:
        deadline = t0 + args.duration
        while time.monotonic() < deadline:
            time.sleep(min(1.0, deadline - time.monotonic()) or 0.1)
            elapsed = time.monotonic() - t0
            print(f"[{elapsed:5.1f}s] sent={sent.get_value()} "
                  f"errors={errors_count.get_value()} "
                  f"avg={recorder.latency():.0f}us "
                  f"p99={recorder.latency_percentile(0.99):.0f}us")
    except KeyboardInterrupt:
        pass
    stop.set()
    for t in threads:
        t.join(5)
    elapsed = time.monotonic() - t0
    total = sent.get_value()
    print(f"\ntotal={total} qps={total / elapsed:.1f} "
          f"errors={errors_count.get_value()} "
          f"avg={recorder.latency():.0f}us "
          f"p50={recorder.latency_percentile(0.5):.0f}us "
          f"p90={recorder.latency_percentile(0.9):.0f}us "
          f"p99={recorder.latency_percentile(0.99):.0f}us")


if __name__ == "__main__":
    main()
