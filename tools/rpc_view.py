#!/usr/bin/env python
"""rpc_view — view another server's builtin console pages from the CLI.

Counterpart of tools/rpc_view (/root/reference/tools/rpc_view/): fetches
/status /vars /flags /connections /rpcz ... from a remote brpc_tpu server.

Usage:
  python tools/rpc_view.py 127.0.0.1:8000 [page] [--watch N]
"""
from __future__ import annotations

import argparse
import http.client
import sys
import time


def fetch(target: str, page: str) -> str:
    host, _, port = target.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80), timeout=5)
    conn.request("GET", f"/{page.lstrip('/')}")
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    if r.status != 200:
        return f"HTTP {r.status}\n{body}"
    return body


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("target", help="ip:port of the server")
    ap.add_argument("page", nargs="?", default="status")
    ap.add_argument("--watch", type=float, default=0,
                    help="refresh every N seconds")
    args = ap.parse_args()
    try:
        while True:
            out = fetch(args.target, args.page)
            if args.watch:
                print("\033[2J\033[H", end="")  # clear screen
            print(out)
            if not args.watch:
                break
            time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
