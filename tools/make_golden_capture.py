#!/usr/bin/env python
"""Regenerate the committed golden capture benchgate replays.

tests/data/golden_capture_1k.rio is a deterministic 1000-request
tpu_std capture (seeded payload sizes, recordio format —
butil/recordio.py) that bench.py's ``replay_qps`` lane re-fires through
the native replay client against the bench echo server. Committing the
capture (not just this generator) keeps the lane byte-stable across
rounds: a qps change is a runtime regression, never a workload drift.

Usage: python tools/make_golden_capture.py [out_path]
"""
from __future__ import annotations

import os
import random
import sys

sys.path.insert(0, ".")

N_RECORDS = 1000
SEED = 20260804


def main():
    from brpc_tpu.butil.recordio import RecordWriter

    out = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        "tests", "data", "golden_capture_1k.rio")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    if os.path.exists(out):
        os.unlink(out)  # RecordWriter appends; the capture must be exact
    rng = random.Random(SEED)
    with RecordWriter(out) as w:
        for i in range(N_RECORDS):
            # production-shaped size mix: mostly small, a long tail
            size = rng.choice((16, 16, 32, 64, 128, 256, 1024))
            payload = bytes((i + j * 7) % 256 for j in range(size))
            w.write({"service": "EchoService", "method": "Echo",
                     "log_id": i, "ts": 0.0, "lane": "echo"}, payload)
    print(f"wrote {N_RECORDS} records to {out} "
          f"({os.path.getsize(out)} bytes)")


if __name__ == "__main__":
    main()
