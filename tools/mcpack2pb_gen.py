#!/usr/bin/env python
"""mcpack2pb code generator CLI — the mcpack2pb/generator.cpp front door.

    python tools/mcpack2pb_gen.py brpc_tpu.rpc.proto.echo_pb2:EchoRequest \
        brpc_tpu.rpc.proto.echo_pb2:EchoResponse -o echo_mcpack.py

    python tools/mcpack2pb_gen.py --service mymod:EchoService -o adaptor.py
"""
import argparse
import importlib
import sys

sys.path.insert(0, ".")


def _resolve(spec: str):
    module_name, _, attr = spec.partition(":")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("specs", nargs="+",
                    help="module:MessageClass (or module:ServiceClass "
                         "with --service)")
    ap.add_argument("--service", action="store_true",
                    help="generate an nshead-mcpack adaptor for an "
                         "rpc.Service subclass")
    ap.add_argument("-o", "--output", default="-",
                    help="output file (default stdout)")
    args = ap.parse_args(argv)

    from brpc_tpu.mcpack2pb_gen import (
        generate_codec_source,
        generate_nshead_adaptor_source,
    )

    if args.service:
        if len(args.specs) != 1:
            ap.error("--service takes exactly one module:ServiceClass")
        src = generate_nshead_adaptor_source(_resolve(args.specs[0]))
    else:
        src = generate_codec_source([_resolve(s) for s in args.specs])
    if args.output == "-":
        sys.stdout.write(src)
    else:
        with open(args.output, "w") as f:
            f.write(src)
        print(f"wrote {args.output}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
