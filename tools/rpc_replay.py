#!/usr/bin/env python
"""rpc_replay — replays rpc_dump recordio samples against a live server.

Counterpart of tools/rpc_replay (/root/reference/tools/rpc_replay/): reads
the recordio files produced by -rpc_dump (brpc_tpu/rpc/rpc_dump.py) and
re-issues each sampled request, optionally qps-throttled.

Usage:
  python tools/rpc_replay.py --dir ./rpc_dump --server 127.0.0.1:8000 \
      [--qps 100] [--times 1]
"""
from __future__ import annotations

import argparse
import glob
import sys
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True, help="rpc_dump directory")
    ap.add_argument("--server", required=True)
    ap.add_argument("--qps", type=float, default=0)
    ap.add_argument("--times", type=int, default=1)
    ap.add_argument("--timeout-ms", type=float, default=1000)
    args = ap.parse_args()

    from brpc_tpu import rpc
    from brpc_tpu.butil.recordio import RecordReader

    files = sorted(glob.glob(f"{args.dir}/*.rio"))
    if not files:
        print(f"no .rio files under {args.dir}", file=sys.stderr)
        return 1

    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=args.timeout_ms))
    if ch.init(args.server) != 0:
        print("channel init failed", file=sys.stderr)
        return 1

    interval = 1.0 / args.qps if args.qps > 0 else 0
    ok = fail = 0
    t0 = time.monotonic()
    for _ in range(args.times):
        for path in files:
            with RecordReader(path) as reader:
                for meta, payload in reader:
                    method = f"{meta['service']}.{meta['method']}"
                    # replay raw payload bytes; response left unparsed
                    cntl, _ = ch.call(method, payload, None,
                                      log_id=meta.get("log_id", 0))
                    if cntl.failed():
                        fail += 1
                    else:
                        ok += 1
                    if interval:
                        time.sleep(interval)
    dt = time.monotonic() - t0
    print(f"replayed ok={ok} failed={fail} in {dt:.1f}s "
          f"({(ok + fail) / dt:.1f} qps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
