#!/usr/bin/env python
"""rpc_replay — replays rpc_dump recordio samples against a live server.

Counterpart of tools/rpc_replay (/root/reference/tools/rpc_replay/): reads
the recordio files produced by -rpc_dump (brpc_tpu/rpc/rpc_dump.py) or by
the native flight recorder (native/src/nat_dump.cpp — same format) and
re-issues each sampled request, optionally qps-throttled.

--native re-fires the capture through the native replay client
(nat_replay_run): tpu_std/HTTP/gRPC records go through the real native
client lanes from a worker-thread pool, with an optional linear qps ramp
(--qps-to) and latency quantiles recorded — the rpc_press-grade load
mode over captured traffic.

Usage:
  python tools/rpc_replay.py --dir ./rpc_dump --server 127.0.0.1:8000 \
      [--qps 100] [--times 1] [--native [--qps-to 500] [--concurrency 8]]
"""
from __future__ import annotations

import argparse
import glob
import sys
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", required=True, help="rpc_dump directory")
    ap.add_argument("--server", required=True)
    ap.add_argument("--qps", type=float, default=0)
    ap.add_argument("--times", type=int, default=1)
    ap.add_argument("--timeout-ms", type=float, default=1000)
    ap.add_argument("--native", action="store_true",
                    help="replay through the native client lanes "
                         "(nat_replay_run)")
    ap.add_argument("--qps-to", type=float, default=0,
                    help="with --native: ramp the rate linearly from "
                         "--qps to this across the run")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="with --native: worker threads firing calls")
    args = ap.parse_args()

    if args.native:
        from brpc_tpu import native

        ip, _, port = args.server.rpartition(":")
        res = native.replay_run(ip or "127.0.0.1", int(port), args.dir,
                                times=args.times, qps=args.qps,
                                qps_to=args.qps_to,
                                concurrency=args.concurrency,
                                timeout_ms=int(args.timeout_ms))
        print(f"replayed ok={res['ok']} failed={res['failed']} "
              f"skipped={res['skipped']} in {res['seconds']:.1f}s "
              f"({res['qps']:.1f} qps) "
              f"p50={res['p50_us']:.0f}us p99={res['p99_us']:.0f}us")
        return 1 if res["failed"] else 0

    from brpc_tpu import rpc
    from brpc_tpu.butil.recordio import RecordReader

    files = sorted(glob.glob(f"{args.dir}/*.rio"))
    if not files:
        print(f"no .rio files under {args.dir}", file=sys.stderr)
        return 1

    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=args.timeout_ms))
    if ch.init(args.server) != 0:
        print("channel init failed", file=sys.stderr)
        return 1

    interval = 1.0 / args.qps if args.qps > 0 else 0
    ok = fail = skipped = 0
    t0 = time.monotonic()
    for _ in range(args.times):
        for path in files:
            with RecordReader(path) as reader:
                for meta, payload in reader:
                    # native mixed-lane captures: only tpu_std records
                    # are replayable through this Channel — firing an
                    # HTTP/redis/worker record as "service.method"
                    # would be a guaranteed bogus call (use --native
                    # for the other lanes)
                    if meta.get("lane", "echo") != "echo":
                        skipped += 1
                        continue
                    method = f"{meta['service']}.{meta['method']}"
                    # replay raw payload bytes; response left unparsed
                    cntl, _ = ch.call(method, payload, None,
                                      log_id=meta.get("log_id", 0))
                    if cntl.failed():
                        fail += 1
                    else:
                        ok += 1
                    if interval:
                        time.sleep(interval)
    dt = time.monotonic() - t0
    print(f"replayed ok={ok} failed={fail} skipped={skipped} "
          f"in {dt:.1f}s ({(ok + fail) / max(dt, 1e-9):.1f} qps)")
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(main())
