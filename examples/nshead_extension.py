#!/usr/bin/env python
"""nshead_extension — example/nshead_{extension,pb_extension}_c++
counterpart: a raw NsheadService AND a GENERATED pb front-end (the
mcpack2pb codegen output) behind Baidu's 36-byte nshead framing.

  python examples/nshead_extension.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import mcpack2pb as mp  # noqa: E402
from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.mcpack2pb_gen import (  # noqa: E402
    compile_codec,
    generate_nshead_adaptor_source,
)
from brpc_tpu.rpc.nshead_protocol import NsheadMessage  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message.swapcase()


def main():
    # generate the pb-over-mcpack adaptor from the service's descriptors —
    # what mcpack2pb/generator.cpp does at build time in the reference
    src = generate_nshead_adaptor_source(EchoService)
    adaptor_cls = compile_codec(src, "echo_nshead").EchoServiceNsheadAdaptor
    srv = rpc.Server(rpc.ServerOptions(
        nshead_service=adaptor_cls(EchoService())))
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead",
                                        timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0
    body = mp.enc_object("", [mp.enc_str("method", "Echo"),
                              mp.enc_str("message", "Hello NSHEAD")])
    cntl, resp = ch.call("nshead", NsheadMessage(body), NsheadMessage)
    assert not cntl.failed(), cntl.error_text
    out = mp.loads(resp.body)
    msg = out["message"]
    if isinstance(msg, bytes):
        msg = msg.decode()
    print(f"nshead-mcpack reply: {msg!r}")
    ch.close()
    srv.stop()
    return 0 if msg == "hELLO nshead" else 1


if __name__ == "__main__":
    sys.exit(main())
