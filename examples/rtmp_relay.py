"""rtmp_relay — a live RTMP relay server (publish -> play) with an FLV
dump, the example/rtmp-family twin: one port accepts RTMP publishers and
players (and still answers RPC/HTTP/redis/... beside them); media pushed
by the publisher is relayed live and muxed into an FLV file.

Run: python examples/rtmp_relay.py
"""
import io
import os
import struct
import sys
import time

_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _repo)

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc import amf, flv  # noqa: E402
from brpc_tpu.rpc import rtmp_protocol as rtmp  # noqa: E402


def main():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       rtmp_service=rtmp.RtmpService()))
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    print(f"rtmp server on rtmp://127.0.0.1:{port}/live")

    # in-process publisher + player on the public client-session API
    # (a stand-in for OBS + a video player)
    pconn, pub = rtmp.rtmp_client_connect("127.0.0.1", port)
    pub.send_command("createStream", 2.0, None)
    pub.send_command("publish", 3.0, None, "demo", "live", stream_id=1)
    pub.pump(want=2)

    vconn, ply = rtmp.rtmp_client_connect("127.0.0.1", port)
    ply.send_command("createStream", 2.0, None)
    ply.send_command("play", 4.0, None, "demo", stream_id=1)
    ply.pump(want=1)
    ply.inbox.clear()

    # publish a tiny synthetic stream
    pub.send_message(rtmp.MSG_DATA_AMF0, 0,
                     amf.encode_many("onMetaData",
                                     {"width": 64.0, "height": 48.0}),
                     stream_id=1)
    for i in range(5):
        payload = b"\x27\x01" + struct.pack(">I", i) + b"frame" * 20
        pub.send_message(rtmp.MSG_VIDEO, i * 33, payload, stream_id=1)

    ply.pump_until(
        lambda s: sum(1 for t, _, _ in s.inbox
                      if t == rtmp.MSG_VIDEO) >= 5)
    out = io.BytesIO()
    w = flv.FlvWriter(out, has_audio=False)
    frames = 0
    for msg_type, ts, payload in ply.inbox:
        if msg_type == rtmp.MSG_VIDEO:
            w.write_video(ts, payload)
            frames += 1
        elif msg_type == rtmp.MSG_DATA_AMF0:
            w.write_metadata(ts, payload)
    tags = list(flv.read_tags(out.getvalue()))
    print(f"relayed {frames} video frames; FLV dump = {len(out.getvalue())}"
          f" bytes, {len(tags)} tags")
    assert frames >= 5, "relay dropped frames"

    # ...and the same payloads packetize into an HLS-style TS segment
    from brpc_tpu.rpc import mpegts

    ts = mpegts.TsMuxer(has_audio=False)
    for msg_type, t, payload in ply.inbox:
        if msg_type == rtmp.MSG_VIDEO:
            ts.write_video(t, payload)
    seg = ts.packets()
    demuxed = sum(1 for _ in mpegts.demux(seg))
    print(f"TS segment = {len(seg) // mpegts.TS_PACKET} packets, "
          f"{demuxed} PES demuxed")

    # edge-pull topology: a SECOND relay server pulls "demo" from the
    # first over the digest-handshake RtmpClient and serves its own
    # players — the CDN-edge shape (rtmp.h RtmpClient/RtmpClientStream)
    from brpc_tpu.rpc import rtmp_client as rclient

    edge_svc = rtmp.RtmpService()
    edge = rpc.Server(rpc.ServerOptions(num_threads=4,
                                        rtmp_service=edge_svc))
    assert edge.start("127.0.0.1:0") == 0
    puller = rclient.pull_into_service(edge_svc, "demo",
                                       "127.0.0.1", port)
    got = []

    def on_edge_media(msg_type, ts_ms, payload):
        if msg_type == rtmp.MSG_VIDEO:
            got.append(payload)

    edge_player = rclient.RtmpClient(
        "127.0.0.1", edge.listen_endpoint.port).connect()
    assert edge_player.digest_mode  # the digest handshake was used
    edge_player.start_reader()
    edge_player.create_stream().play("demo", on_edge_media)
    deadline = time.monotonic() + 10
    while len(got) < 3 and time.monotonic() < deadline:
        pub.send_message(rtmp.MSG_VIDEO, 999, b"\x27edgeframe",
                         stream_id=1)
        time.sleep(0.1)
    assert len(got) >= 3, "edge pull relayed nothing"
    print(f"edge server relayed {len(got)} frames pulled from the origin "
          f"(digest handshake)")
    edge_player.close()
    puller.close()
    edge.stop()

    pconn.close()
    vconn.close()
    time.sleep(0.1)
    srv.stop()
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
