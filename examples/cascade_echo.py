#!/usr/bin/env python
"""cascade_echo — a call that hops through a chain of servers, with rpcz
tracing the whole path (example/cascade_echo_c++ counterpart; the
pipeline-stage shape of SURVEY.md section 2.12).

  python examples/cascade_echo.py [--depth 3]
"""
import argparse
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc, rpcz  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class CascadeService(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, name, next_channel=None):
        self.name = name
        self.next_channel = next_channel

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if self.next_channel is not None:
            _, next_resp = self.next_channel.call(
                "EchoService.Echo",
                echo_pb2.EchoRequest(message=request.message),
                echo_pb2.EchoResponse, timeout_ms=3000)
            response.message = f"{self.name}->{next_resp.message}"
        else:
            response.message = self.name
        done()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=3)
    args = ap.parse_args()

    servers = []
    next_ch = None
    for i in reversed(range(args.depth)):
        srv = rpc.Server()
        srv.add_service(CascadeService(f"hop{i}", next_ch))
        assert srv.start("127.0.0.1:0") == 0
        servers.append(srv)
        next_ch = rpc.Channel()
        assert next_ch.init(str(srv.listen_endpoint)) == 0

    rpcz.clear_for_tests()
    cntl, resp = rpc.Channel(), echo_pb2.EchoResponse()
    head = rpc.Channel()
    assert head.init(str(servers[-1].listen_endpoint)) == 0
    cntl, resp = head.call("EchoService.Echo",
                           echo_pb2.EchoRequest(message="go"),
                           echo_pb2.EchoResponse, timeout_ms=5000)
    print("cascade result:", resp.message)

    import time

    time.sleep(0.1)
    spans = rpcz.recent_spans()
    traces = {s.trace_id for s in spans}
    print(f"rpcz collected {len(spans)} spans in {len(traces)} trace(s):")
    for s in spans:
        print("  ", s.describe().splitlines()[0])
    for srv in servers:
        srv.stop()


if __name__ == "__main__":
    main()
