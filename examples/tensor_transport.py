#!/usr/bin/env python
"""tensor_transport — the rdma_performance counterpart
(example/rdma_performance/): pushes/pulls device tensors through the
TensorStore service over a device-handshaked channel and reports achieved
throughput, then probes raw collective bandwidth on the mesh.

  python examples/tensor_transport.py [--mb 8] [--iters 10]
"""
import argparse
import sys
import time

sys.path.insert(0, ".")

import _jaxenv  # noqa: E402

_jaxenv.apply()

import numpy as np  # noqa: E402

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.tensor_service import (  # noqa: E402
    TensorClient,
    TensorStoreService,
    make_device_channel,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=8)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    svc = TensorStoreService()
    srv = rpc.Server()
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    ch = make_device_channel(str(srv.listen_endpoint))
    client = TensorClient(ch)

    import jax.numpy as jnp

    nbytes = args.mb << 20
    arr = jnp.zeros((nbytes // 4,), jnp.float32)
    # warm
    cntl, _ = client.push("warm", [arr])
    assert not cntl.failed(), cntl.error_text
    sock = cntl._current_sock
    print(f"endpoint state: {sock.app_state.state} "
          f"(2=ESTABLISHED, 3=FALLBACK_TCP), "
          f"same_process={sock.app_state.same_process}")

    t0 = time.perf_counter()
    for i in range(args.iters):
        cntl, _ = client.push(f"t{i}", [arr])
        assert not cntl.failed(), cntl.error_text
    dt = time.perf_counter() - t0
    total = nbytes * args.iters
    print(f"pushed {args.iters} x {args.mb}MB in {dt:.3f}s "
          f"-> {total / dt / 1e9:.2f} GB/s "
          f"(zero-copy in-process device lane)")

    cntl, pulled = client.pull("t0")
    assert pulled is not None
    np.testing.assert_allclose(np.asarray(pulled[0])[:8],
                               np.asarray(arr)[:8])
    print("pull verified")

    # cross-process lane: a subprocess server, payloads via the shared
    # HostArena (descriptor-only wire — the rdma_performance shape)
    import subprocess
    import sys as _sys

    script = (
        "import sys; sys.path.insert(0, '.');\n"
        "import _jaxenv; _jaxenv.apply()\n"
        "from brpc_tpu import rpc\n"
        "from brpc_tpu.rpc.tensor_service import TensorStoreService\n"
        "srv = rpc.Server(rpc.ServerOptions(num_threads=2))\n"
        "srv.add_service(TensorStoreService())\n"
        "assert srv.start('127.0.0.1:0') == 0\n"
        "print(srv.listen_endpoint.port, flush=True)\n"
        "sys.stdin.readline()\n"
        "srv.stop()\n"
    )
    proc = subprocess.Popen([_sys.executable, "-c", script],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd=".",
                            env={**__import__('os').environ,
                                 "PYTHONPATH": "examples"})
    xport = int(proc.stdout.readline())
    xch = make_device_channel(f"127.0.0.1:{xport}")
    xclient = TensorClient(xch)
    from brpc_tpu.rpc import device_transport as _dt

    lanes0 = _dt.lane_counters()
    cntl, _ = xclient.push("xwarm", [arr])
    assert not cntl.failed(), cntl.error_text
    ep = cntl._current_sock.app_state
    t0 = time.perf_counter()
    for i in range(args.iters):
        cntl, _ = xclient.push(f"x{i}", [arr])
        assert not cntl.failed(), cntl.error_text
    dtx = time.perf_counter() - t0
    lanes1 = _dt.lane_counters()
    # this process hosted its own in-process server above, so it owns a
    # fabric segment of its own — the push falls back to the shared
    # HostArena lane; a pure client process would ride the ring fabric
    lane = next((k for k in ("ring", "shm", "wire")
                 if lanes1[k] > lanes0[k]), "?")
    print(f"cross-process pushed {args.iters} x {args.mb}MB in {dtx:.3f}s "
          f"-> {nbytes * args.iters / dtx / 1e9:.2f} GB/s "
          f"({lane} lane, same_host={ep.same_host}, "
          f"same_process={ep.same_process})")
    xch.close()
    proc.stdin.close()
    proc.wait(timeout=10)

    import jax

    if len(jax.devices()) >= 2:
        from brpc_tpu import parallel

        n = len(jax.devices())
        mesh = parallel.make_mesh({"x": n})
        stats = parallel.ici_bandwidth_probe(mesh, "x", nbytes=1 << 22,
                                             iters=5)
        print(f"mesh allreduce over {n} devices: "
              f"{stats['allreduce_GBps']:.2f} GB/s")
    srv.stop()


if __name__ == "__main__":
    main()
