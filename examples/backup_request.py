#!/usr/bin/env python
"""backup_request + cancel — tail-latency tools
(example/backup_request_c++ and example/cancel_c++ counterparts).

  python examples/backup_request.py
"""
import sys
import time

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc import errors  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class SlowEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        time.sleep(0.5)
        response.message = "slow"
        done()


class FastEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "fast"
        done()


def main():
    slow = rpc.Server()
    slow.add_service(SlowEcho())
    assert slow.start("127.0.0.1:0") == 0
    fast = rpc.Server()
    fast.add_service(FastEcho())
    assert fast.start("127.0.0.1:0") == 0

    # backup fires after 50ms; when rr lands on the slow node, the backup
    # attempt rescues the tail (controller.cpp:1256 path)
    ch = rpc.Channel(rpc.ChannelOptions(backup_request_ms=50, max_retry=2))
    assert ch.init(f"list://{slow.listen_endpoint},{fast.listen_endpoint}",
                   "rr") == 0
    for i in range(4):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="x"),
                             echo_pb2.EchoResponse, timeout_ms=3000)
        print(f"call {i}: reply={resp.message} backup="
              f"{cntl.has_backup_request} latency={cntl.latency_us/1000:.0f}ms")

    # cancel: abort an in-flight slow call (StartCancel analog)
    slow_ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=5000))
    assert slow_ch.init(str(slow.listen_endpoint)) == 0
    cntl = rpc.Controller()
    resp = echo_pb2.EchoResponse()
    import threading

    threading.Timer(0.05, cntl.cancel).start()
    slow_ch.call_method("EchoService.Echo", cntl,
                        echo_pb2.EchoRequest(message="c"), resp)
    assert cntl.error_code == errors.ECANCELED
    print(f"cancelled call ended with: {cntl.error_text} "
          f"after {cntl.latency_us/1000:.0f}ms")

    slow.stop()
    fast.stop()


if __name__ == "__main__":
    main()
