#!/usr/bin/env python
"""session_data — example/session_data_and_thread_local counterpart:
session-local data borrowed from a SimpleDataPool per request and
returned afterwards, so expensive per-request state is pooled.

  python examples/session_data.py
"""
import sys
import threading

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.data_pools import DataFactory  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402

_created = []


def _make_session():
    _created.append(1)
    return {"uses": 0}


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            data = cntl.session_local_data  # borrowed from the pool
            data["uses"] += 1
            response.message = f"{request.message} (session uses="
            response.message += f"{data['uses']})"


def main():
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2,
        session_local_data_factory=DataFactory(_make_session)))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0
    for i in range(10):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message=f"req{i}"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
    print(f"10 sequential requests reused "
          f"{len(_created)} pooled session object(s)")
    ch.close()
    srv.stop()
    # sequential calls should reuse a small pool, not create 10 objects
    return 0 if len(_created) < 10 else 1


if __name__ == "__main__":
    sys.exit(main())
