"""Make examples honor JAX_PLATFORMS.

The environment's sitecustomize may pre-select a platform through
jax.config (which overrides the JAX_PLATFORMS env var); the test runner
forces the virtual-CPU mesh via that env var, so re-apply it here before
any backend initializes.
"""
import os


def apply():
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
