#!/usr/bin/env python
"""legacy_pbrpc_echo — the Baidu legacy pb-rpc family on one port: the
same service answers hulu_pbrpc, sofa_pbrpc, nshead and tpu_std
simultaneously (the multi-protocol port, server.cpp's protocol trying).

  python examples/legacy_pbrpc_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message


def main():
    srv = rpc.Server()
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    target = str(srv.listen_endpoint)

    rc = 0
    for protocol in ("hulu_pbrpc", "sofa_pbrpc", "tpu_std"):
        ch = rpc.Channel(rpc.ChannelOptions(protocol=protocol,
                                            timeout_ms=1000))
        assert ch.init(target) == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message=f"via {protocol}"),
                             echo_pb2.EchoResponse)
        if cntl.failed():
            print(f"{protocol}: FAILED {cntl.error_text}")
            rc = 1
        else:
            print(f"{protocol}: {resp.message!r} "
                  f"({cntl.latency_us:.0f}us)")
        ch.close()
    srv.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
