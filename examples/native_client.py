"""Native-client features tour: deadlines, async done-callbacks, retry
semantics, and kill-and-revive reconnection — the brpc client Controller
feature set (controller.cpp:605 timeouts, health_check.cpp revival) on
the NATIVE C++ runtime, driven from Python via ctypes.

Run: python examples/native_client.py
"""
import sys
import threading
import time

sys.path.insert(0, ".")

from brpc_tpu import native  # noqa: E402


def main():
    if not native.available():
        print("native toolchain unavailable; nothing to demo")
        return

    port = native.rpc_server_start(native_echo=True)
    print(f"native server on 127.0.0.1:{port}")
    ch = native.channel_open("127.0.0.1", port, connect_timeout_ms=2000,
                             health_check_ms=50)

    # 1. synchronous call with a generous deadline
    rc, body, err = native.channel_call(ch, "EchoService", "Echo",
                                        b"hello-native", timeout_ms=2000)
    assert rc == 0 and body == b"hello-native", (rc, err)
    print("sync echo:", body.decode())

    # 2. async done-callback
    done_evt = threading.Event()

    def done(code, resp):
        print(f"async done: code={code} resp={resp.decode()}")
        done_evt.set()

    assert native.channel_acall(ch, "EchoService", "Echo", b"async-hi",
                                done, timeout_ms=2000) == 0
    assert done_evt.wait(5)

    # 3. deadline against a stalled method (no such handler + nobody
    #    drains the py lane -> the request parks forever; the native
    #    TimerThread fails the call in ~150ms with ERPCTIMEDOUT=1008)
    t0 = time.monotonic()
    rc, _, err = native.channel_call(ch, "NoSuch", "Stall", b"x",
                                     timeout_ms=150)
    dt_ms = (time.monotonic() - t0) * 1000
    print(f"deadline: rc={rc} ({err}) after {dt_ms:.0f}ms")
    assert rc == 1008

    # 4. kill-and-revive: stop the server, watch calls fail fast, restart
    #    on the same port, and let the channel re-dial on demand
    native.rpc_server_stop()
    rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"down",
                                   timeout_ms=300)
    print(f"server down: rc={rc}")
    assert rc != 0
    port2 = native.rpc_server_start(port=port, native_echo=True)
    assert port2 == port
    deadline = time.monotonic() + 10
    rc = -1
    while time.monotonic() < deadline:
        rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                          b"revived", timeout_ms=1000)
        if rc == 0:
            break
        time.sleep(0.05)
    assert rc == 0 and body == b"revived"
    print("revived:", body.decode())

    native.channel_close(ch)
    native.rpc_server_stop()
    print("OK")


if __name__ == "__main__":
    main()
