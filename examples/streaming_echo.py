#!/usr/bin/env python
"""streaming_echo — Streams with flow control (example/streaming_echo_c++
counterpart): the client opens a stream on an RPC, pushes chunks, the
server echoes them back on the same stream.

  python examples/streaming_echo.py
"""
import sys
import threading
import time

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class StreamingEchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Open(self, cntl, request, response, done):
        class EchoBack(rpc.StreamInputHandler):
            def on_received_messages(self, stream, messages):
                for m in messages:
                    stream.write(m)

            def on_closed(self, stream):
                print("[server] stream closed")

        stream = rpc.stream_accept(cntl,
                                   rpc.StreamOptions(handler=EchoBack()))
        response.message = "accepted" if stream else "no stream"
        done()


def main():
    srv = rpc.Server()
    srv.add_service(StreamingEchoService())
    assert srv.start("127.0.0.1:0") == 0

    got = []
    done_ev = threading.Event()

    class Collect(rpc.StreamInputHandler):
        def on_received_messages(self, stream, messages):
            for m in messages:
                got.append(m.to_bytes())
            if len(got) >= 5:
                done_ev.set()

        def on_closed(self, stream):
            print("[client] stream closed")

    ch = rpc.Channel()
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl = rpc.Controller()
    cntl.timeout_ms = 3000
    stream = rpc.stream_create(cntl, rpc.StreamOptions(handler=Collect()))
    resp = echo_pb2.EchoResponse()
    ch.call_method("StreamingEchoService.Open", cntl,
                   echo_pb2.EchoRequest(message="open"), resp)
    assert not cntl.failed(), cntl.error_text
    stream.wait_connected(3)
    for i in range(5):
        stream.write(f"chunk-{i}".encode())
    done_ev.wait(5)
    print("echoed back:", got)
    stream.close()
    time.sleep(0.1)
    srv.stop()


if __name__ == "__main__":
    main()
