#!/usr/bin/env python
"""echo — the canonical example (example/echo_c++ counterpart).

  python examples/echo.py server [--port 8000]
  python examples/echo.py client [--server 127.0.0.1:8000] [--attachment x]
  python examples/echo.py demo          # both in one process
"""
import argparse
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message
            # echo the attachment exactly as example/echo_c++ does
            cntl.response_attachment.append(cntl.request_attachment)


def run_server(port: int) -> rpc.Server:
    srv = rpc.Server()
    srv.add_service(EchoService())
    assert srv.start(f"127.0.0.1:{port}") == 0
    print(f"echo server on {srv.listen_endpoint} "
          f"(console: http://{srv.listen_endpoint}/status)")
    return srv


def run_client(target: str, attachment: str):
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1000))
    assert ch.init(target) == 0
    cntl = rpc.Controller()
    if attachment:
        cntl.request_attachment.append(attachment)
    resp = echo_pb2.EchoResponse()
    ch.call_method("EchoService.Echo", cntl,
                   echo_pb2.EchoRequest(message="hello tpu"), resp)
    if cntl.failed():
        print("failed:", cntl.error_text)
        return 1
    print(f"reply={resp.message!r} attachment="
          f"{cntl.response_attachment.to_bytes()!r} "
          f"latency={cntl.latency_us:.0f}us")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["server", "client", "demo"])
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--server", default="127.0.0.1:8000")
    ap.add_argument("--attachment", default="")
    args = ap.parse_args()
    if args.mode == "server":
        run_server(args.port).run_until_asked_to_quit()
    elif args.mode == "client":
        sys.exit(run_client(args.server, args.attachment))
    else:
        srv = run_server(0)
        rc = run_client(str(srv.listen_endpoint), "piggy-bytes")
        srv.stop()
        sys.exit(rc)
