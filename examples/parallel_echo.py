#!/usr/bin/env python
"""parallel_echo — ParallelChannel fan-out (example/parallel_echo_c++
counterpart) plus its fused-device twin: the same call shape executed as
ONE XLA collective through MeshChannel (SURVEY.md section 2.12).

  python examples/parallel_echo.py
"""
import sys

sys.path.insert(0, ".")

import _jaxenv  # noqa: E402

_jaxenv.apply()

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class NodeEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, name):
        self.name = name

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = f"{self.name}:{request.message};"
        done()


class ConcatMerger(rpc.ResponseMerger):
    def merge(self, main, sub):
        main.message += sub.message
        return 0


def main():
    servers = []
    pc = rpc.ParallelChannel()
    for i in range(3):
        srv = rpc.Server()
        srv.add_service(NodeEcho(f"node{i}"))
        assert srv.start("127.0.0.1:0") == 0
        servers.append(srv)
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        pc.add_channel(ch, response_merger=ConcatMerger())

    cntl, resp = pc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="fanout"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    print("RPC fan-out merged:", resp.message, f"({cntl.latency_us:.0f}us)")

    # The fused twin: same semantics, one device program.
    import jax

    if len(jax.devices()) >= 2:
        import jax.numpy as jnp

        from brpc_tpu import parallel

        n = len(jax.devices())
        mesh = parallel.make_mesh({"dp": n})
        mc = parallel.MeshChannel(mesh, "dp")
        shards = jnp.arange(float(n)).reshape(n, 1)
        merged = mc.parallel_call(lambda s: s * 2.0, shards, merger="add")
        print(f"Mesh fan-out (ONE allreduce over {n} devices):",
              float(merged.ravel()[0]))
    for srv in servers:
        srv.stop()


if __name__ == "__main__":
    main()
