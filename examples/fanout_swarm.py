"""fanout_swarm — the native fan-out demo (ISSUE 13, ROADMAP item 1).

Spins N in-process backends (one native echo server listening on N
ports — the multi-port swarm seam), puts a native PartitionChannel and
a native cluster in front of them with a LIVE file naming service, then
demonstrates the three things the native fan-out core exists for:

  1. parallel fan-out + native merge across every backend (the
     ParallelChannel verb: one call, N concurrent sub-calls on fibers,
     responses merged in C++);
  2. live naming updates: the server-list file is rewritten while
     selective traffic flows — the DoublyBufferedData swap + reader
     quiesce re-balances with zero dropped calls;
  3. a rolling-restart loop: listeners are removed and re-added port by
     port while a selective flood runs — the per-backend breakers,
     transport cool-downs and failover retry keep every RPC whole.

Run:  python examples/fanout_swarm.py [--backends 16] [--seconds 6]
"""
import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import _jaxenv  # noqa: F401,E402  (pins jax to cpu for the demo)

from brpc_tpu import native  # noqa: E402
from brpc_tpu.rpc.combo_channels import PartitionChannel  # noqa: E402
from brpc_tpu.rpc.native_cluster import NativeCluster  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--backends", type=int, default=16)
    ap.add_argument("--seconds", type=float, default=6.0)
    args = ap.parse_args()

    # --- the swarm: one native echo server, N listening ports ---------
    port = native.rpc_server_start(native_echo=True)
    ports = [port] + [native.rpc_server_add_port()
                      for _ in range(args.backends - 1)]
    print(f"swarm: {len(ports)} backends "
          f"(ports {ports[0]}..{ports[-1]})")

    # --- live naming: a server-list file the watcher re-reads ---------
    nf = tempfile.NamedTemporaryFile("w", suffix=".swarm.ns",
                                     delete=False)

    def write_naming(plist, partitioned=False):
        with open(nf.name, "w") as f:
            for i, p in enumerate(plist):
                tag = f" {i % 4}/4" if partitioned else ""
                f.write(f"127.0.0.1:{p}{tag}\n")

    write_naming(ports)
    nf.close()

    try:
        # --- 1. parallel fan-out + native merge -----------------------
        with NativeCluster(lb="rr", name="swarm-demo") as cluster:
            cluster.watch(f"file://{nf.name}")
            rc, body, err, failed = cluster.parallel_call(
                "EchoService.Echo", b"ping", timeout_ms=3000)
            assert rc == 0, err
            print(f"parallel fan-out: {cluster.backend_count()} "
                  f"backends answered in one call "
                  f"(merged {len(body)} bytes, {failed} failed)")

            # --- 2. live naming updates under selective traffic -------
            stop = threading.Event()
            stats = {"calls": 0, "failed": 0}

            def flood():
                while not stop.is_set():
                    rc, _, _ = cluster.call("EchoService.Echo", b"x",
                                            timeout_ms=3000, max_retry=8)
                    stats["calls"] += 1
                    if rc != 0:
                        stats["failed"] += 1

            t = threading.Thread(target=flood)
            t.start()
            deadline = time.time() + args.seconds

            # shrink + regrow the naming file while traffic flows
            write_naming(ports[: len(ports) // 2])
            time.sleep(min(2.5, args.seconds / 2))
            write_naming(ports)

            # --- 3. rolling restarts: remove + re-add listeners -------
            restarted = 0
            while time.time() < deadline and restarted < len(ports) - 1:
                victim = ports[1 + restarted % (len(ports) - 1)]
                native.rpc_server_remove_port(victim)
                time.sleep(0.05)
                native.rpc_server_add_port(port=victim)
                restarted += 1
            stop.set()
            t.join()
            print(f"churn window: {stats['calls']} selective calls, "
                  f"{stats['failed']} failed, {restarted} listener "
                  f"restarts, live naming shrink+regrow")

            spread = sorted(r["selects"] for r in cluster.stats())
            print(f"per-backend selects: min={spread[0]} "
                  f"p50={spread[len(spread) // 2]} max={spread[-1]}")

        # --- the combo-channel face: a native PartitionChannel --------
        write_naming(ports, partitioned=True)
        prt = PartitionChannel(native=True)
        assert prt.init(4, f"file://{nf.name}") == 0
        try:
            from brpc_tpu import rpc
            from brpc_tpu.rpc.proto import echo_pb2

            cntl = rpc.Controller()
            cntl.timeout_ms = 3000
            resp = echo_pb2.EchoResponse()
            prt.call_method("EchoService.Echo", cntl,
                            echo_pb2.EchoRequest(message="sharded"),
                            resp)
            assert not cntl.failed(), cntl.error_text
            print(f"native PartitionChannel (4-way '{'i/4'}' tags): "
                  f"merged response message={resp.message!r}")
        finally:
            prt.stop()
    finally:
        os.unlink(nf.name)
        native.rpc_server_stop()
    print("ok")


if __name__ == "__main__":
    main()
