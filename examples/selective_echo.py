#!/usr/bin/env python
"""selective_echo — example/selective_echo_c++ counterpart: a
SelectiveChannel load-balances whole sub-channels and fails over when a
backend dies mid-run.

  python examples/selective_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc import errors  # noqa: E402
from brpc_tpu.rpc.combo_channels import SelectiveChannel  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class NamedEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, tag):
        self.tag = tag

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = f"{self.tag}:{request.message}"


def main():
    servers = []
    schan = SelectiveChannel(max_retry=2)
    for tag in ("a", "b", "c"):
        srv = rpc.Server()
        srv.add_service(NamedEcho(tag))
        assert srv.start("127.0.0.1:0") == 0
        servers.append(srv)
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=500))
        assert ch.init(str(srv.listen_endpoint)) == 0
        schan.add_channel(ch)

    seen = set()
    for i in range(12):
        cntl, resp = schan.call("EchoService.Echo",
                                echo_pb2.EchoRequest(message=str(i)),
                                echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        seen.add(resp.message.split(":")[0])
    print(f"spread across backends: {sorted(seen)}")

    # kill one backend: calls must fail over to the survivors
    servers[0].stop()
    ok = 0
    for i in range(8):
        cntl, resp = schan.call("EchoService.Echo",
                                echo_pb2.EchoRequest(message=f"x{i}"),
                                echo_pb2.EchoResponse)
        if not cntl.failed():
            ok += 1
    print(f"after killing backend 'a': {ok}/8 succeeded via failover")
    for srv in servers[1:]:
        srv.stop()
    return 0 if len(seen) > 1 and ok == 8 else 1


if __name__ == "__main__":
    sys.exit(main())
