#!/usr/bin/env python
"""partition_echo + selective_echo + dynamic partition — combo channels over
tagged naming (example/partition_echo_c++ / selective_echo_c++ /
dynamic_partition_echo_c++ counterparts).

  python examples/partition_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class PartEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, name):
        self.name = name

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = f"{self.name},"
        done()


class ConcatMerger(rpc.ResponseMerger):
    def merge(self, main, sub):
        main.message += sub.message
        return 0


def main():
    servers = []
    for i in range(3):
        srv = rpc.Server()
        srv.add_service(PartEcho(f"part{i}"))
        assert srv.start("127.0.0.1:0") == 0
        servers.append(srv)

    # ---- PartitionChannel: tags "i/3" shard the service 3 ways
    url = "list://" + ",".join(
        f"{s.listen_endpoint} {i}/3" for i, s in enumerate(servers))
    pc = rpc.PartitionChannel()
    assert pc.init(3, url, "rr") == 0
    for i in range(len(pc._subs)):
        ch, m, _ = pc._subs[i]
        pc._subs[i] = (ch, m, ConcatMerger())
    cntl, resp = pc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="p"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    print("partitioned call hit:", resp.message)
    pc.stop()

    # ---- SelectiveChannel: one healthy channel per call with failover
    sc = rpc.SelectiveChannel()
    dead = rpc.Channel(rpc.ChannelOptions(max_retry=0, timeout_ms=200))
    dead.init("127.0.0.1:1")
    sc.add_channel(dead)
    live = rpc.Channel()
    live.init(str(servers[0].listen_endpoint))
    sc.add_channel(live)
    cntl, resp = sc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="s"),
                         echo_pb2.EchoResponse, timeout_ms=2000)
    print("selective call (with failover past a dead node):", resp.message)

    # ---- DynamicPartitionChannel: 1-way and 2-way schemes co-exist
    url2 = (f"list://{servers[0].listen_endpoint} 0/1,"
            f"{servers[1].listen_endpoint} 0/2,"
            f"{servers[2].listen_endpoint} 1/2")
    dc = rpc.DynamicPartitionChannel()
    assert dc.init(url2, "rr") == 0
    for _ in range(3):
        cntl, resp = dc.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="d"),
                             echo_pb2.EchoResponse, timeout_ms=3000)
        print("dynamic-partition call hit:", resp.message)
    dc.stop()

    for srv in servers:
        srv.stop()


if __name__ == "__main__":
    main()
