#!/usr/bin/env python
"""thrift_echo — example/thrift_extension_c++ counterpart: a ThriftService
handler behind framed-binary thrift, called with a stub-style client.

  python examples/thrift_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.thrift import (  # noqa: E402
    T_STRING,
    ThriftMessage,
    ThriftService,
)


def make_service() -> ThriftService:
    svc = ThriftService()

    def echo(body):  # handler(body_struct) -> result_struct
        data = body.get(1, (T_STRING, b""))[1]
        return {0: (T_STRING, b"thrift says: " + data)}

    svc.add_method("Echo", echo)
    return svc


def main():
    srv = rpc.Server(rpc.ServerOptions(thrift_service=make_service()))
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(protocol="thrift",
                                        timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl = rpc.Controller()
    resp = ThriftMessage()
    ch.call_method("thrift", cntl,
                   ThriftMessage("Echo", {1: (T_STRING, b"hello")}), resp)
    assert not cntl.failed(), cntl.error_text
    _, data = resp.body.get(0, (T_STRING, b""))
    print(f"thrift reply: {data!r}")
    ch.close()
    srv.stop()
    return 0 if data == b"thrift says: hello" else 1


if __name__ == "__main__":
    sys.exit(main())
