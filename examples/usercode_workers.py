"""Usercode worker processes: HTTP/gRPC handler code running across N
Python interpreters (the shm lane, nat_shm_lane.cpp) — the reference's
usercode-on-all-N-workers concurrency (server.h num_threads product)
without this process's GIL in the way.

Run: python examples/usercode_workers.py
"""
import os
import sys

sys.path.insert(0, ".")

from brpc_tpu import native, rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


def make_services():
    """Worker factory: each worker process rebuilds the services."""

    class PidEchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = f"{request.message}@{os.getpid()}"
            done()

    return [PidEchoService()]


def main():
    if not native.available():
        print("native toolchain unavailable; nothing to demo")
        return

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=2,
        py_worker_factory="examples.usercode_workers:make_services"))
    for s in make_services():
        srv.add_service(s)  # the in-process fallback serves these too
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    print(f"server on 127.0.0.1:{port}, usercode in 2 worker processes "
          f"(parent pid {os.getpid()})")

    g = native.channel_open_grpc("127.0.0.1", port)
    pids = set()
    for i in range(12):
        st, body, _ = native.grpc_call(
            g, "/PidEchoService/Echo",
            echo_pb2.EchoRequest(message=f"r{i}").SerializeToString(),
            timeout_ms=15000)
        assert st == 0
        reply = echo_pb2.EchoResponse.FromString(body).message
        pids.add(reply.split("@")[1])
    print(f"12 calls served by pids: {sorted(pids)}")
    # Usually the worker pids; the parent pid appears when the
    # in-process fallback engages (stalled worker heartbeat on a loaded
    # host — by design, so no hard assert here; the guarantees live in
    # tests/test_shm_workers.py).
    worker_pids = pids - {str(os.getpid())}
    if worker_pids:
        print(f"worker processes served calls: {sorted(worker_pids)}")
    else:
        print("note: loaded host — calls served by the in-process "
              "fallback this run")
    native.channel_close(g)
    srv.stop()
    print("ok")


if __name__ == "__main__":
    main()
