#!/usr/bin/env python
"""asynchronous_echo — example/asynchronous_echo_c++ counterpart: issue
the RPC with a done-callback and keep working; the callback runs on
completion (client.cpp's HandleEchoResponse + NewCallback shape).

  python examples/asynchronous_echo.py
"""
import sys
import threading

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message


def main():
    srv = rpc.Server()
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0

    n = 8
    finished = threading.Semaphore(0)
    results = [None] * n

    def make_done(i, cntl, resp):
        def handle(c):  # HandleEchoResponse role — runs on completion
            results[i] = (c.failed(), resp.message)
            finished.release()
        return handle

    for i in range(n):
        cntl = rpc.Controller()
        resp = echo_pb2.EchoResponse()
        ch.call_method("EchoService.Echo", cntl,
                       echo_pb2.EchoRequest(message=f"async {i}"), resp,
                       done=make_done(i, cntl, resp))
        # control returned immediately; the RPC completes in background

    for _ in range(n):
        finished.acquire()
    ok = all(not failed and msg == f"async {i}"
             for i, (failed, msg) in enumerate(results))
    print("async results:", "all ok" if ok else results)
    ch.close()
    srv.stop()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
