#!/usr/bin/env python
"""dynamic_partition_echo — example/dynamic_partition_echo_c++
counterpart: servers announce DIFFERENT partitioning schemes ("N/M" tags)
in one naming service; DynamicPartitionChannel groups them per scheme and
weights scheme choice by live capacity through the _dynpart LB.

  python examples/dynamic_partition_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.combo_channels import (  # noqa: E402
    DynamicPartitionChannel,
    PartitionParser,
)
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class PartEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, part, total):
        self.part, self.total = part, total

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = f"{self.part}/{self.total}:{request.message}"


def main():
    # one 2-way partitioned generation and one 3-way (the migration
    # scenario dynamic partitioning exists for)
    servers, nodes = [], []
    for total in (2, 3):
        for part in range(total):
            srv = rpc.Server()
            srv.add_service(PartEcho(part, total))
            assert srv.start("127.0.0.1:0") == 0
            servers.append(srv)
            nodes.append(f"{srv.listen_endpoint} {part}/{total}")

    dpc = DynamicPartitionChannel()
    rc = dpc.init("list://" + ",".join(nodes), "rr",
                  parser=PartitionParser(),
                  options=rpc.ChannelOptions(timeout_ms=500))
    assert rc == 0, rc

    counts = {2: 0, 3: 0}
    for i in range(20):
        cntl, resp = dpc.call("EchoService.Echo",
                              echo_pb2.EchoRequest(message=str(i)),
                              echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        total = int(resp.message.split(":")[0].split("/")[1])
        counts[total] += 1
    print(f"scheme usage (2-way vs 3-way, capacity-weighted): {counts}")
    dpc.stop()
    for srv in servers:
        srv.stop()
    return 0 if counts[2] and counts[3] else 1


if __name__ == "__main__":
    sys.exit(main())
