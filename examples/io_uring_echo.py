#!/usr/bin/env python
"""io_uring_echo — the RingListener datapath in action: the native RPC
server's reads ride provided-buffer multishot receives and its responses
ride fixed-buffer sends, with completions drained by the fiber scheduler
(the monographdb fork's io_uring lane, bthread/ring_listener.h).

  python examples/io_uring_echo.py [--seconds 2]
"""
import argparse
import sys

sys.path.insert(0, ".")

from brpc_tpu import native, rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=2.0)
    args = ap.parse_args()

    if not native.available():
        print("native toolchain unavailable; nothing to demo")
        return 0
    rc = native.use_io_uring(True)
    if rc != 1:
        print("io_uring unavailable in this kernel/sandbox (epoll remains)")
        return 0
    try:
        port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                       native_echo=True)
        # generous timeout + one retry: the CI box runs the whole suite on
        # one core, and a cold ring lane under that load can miss a tight
        # deadline without anything being wrong
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=15000))
        assert ch.init(f"127.0.0.1:{port}") == 0
        for attempt in (1, 2):
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(
                                     message="over the ring"),
                                 echo_pb2.EchoResponse)
            if not cntl.failed():
                break
        assert not cntl.failed(), cntl.error_text
        print(f"echo reply: {resp.message!r}")
        ch.close()

        import ctypes
        out = ctypes.c_uint64(0)
        qps = native.load().nat_rpc_client_bench(
            b"127.0.0.1", port, 2, 64, args.seconds, 16, ctypes.byref(out))
        recv, send = native.ring_counters()
        print(f"ring-lane framework echo: {qps:.0f} qps "
              f"({out.value} requests)")
        print(f"ring completions: {recv} provided-buffer receives, "
              f"{send} fixed-buffer sends")
        return 0
    finally:
        native.rpc_server_stop()
        native.use_io_uring(False)


if __name__ == "__main__":
    sys.exit(main())
