#!/usr/bin/env python
"""multi_threaded_echo — the example/multi_threaded_echo_c++ counterpart,
on the FRAMEWORK path: N client threads issue synchronous echoes through
Server/Channel/Controller, instrumented with a bvar LatencyRecorder
exactly like the reference client (client.cpp:50-52: g_latency_recorder
<< elapsed; qps/percentiles read back from it).

  python examples/multi_threaded_echo.py [--threads 4] [--seconds 2]
"""
import argparse
import sys
import threading
import time

sys.path.insert(0, ".")

from brpc_tpu import bvar, rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--seconds", type=float, default=2.0)
    args = ap.parse_args()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    target = str(srv.listen_endpoint)

    recorder = bvar.LatencyRecorder("mt_echo_client")
    error_count = bvar.Adder("mt_echo_client_errors")
    stop = threading.Event()

    def sender():
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1000))
        assert ch.init(target) == 0
        i = 0
        while not stop.is_set():
            cntl, resp = ch.call(
                "EchoService.Echo",
                echo_pb2.EchoRequest(message=f"hello {i}"),
                echo_pb2.EchoResponse)
            if cntl.failed():
                error_count.update(1)
            else:
                recorder.update(cntl.latency_us)
            i += 1
        ch.close()

    threads = [threading.Thread(target=sender) for _ in range(args.threads)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + args.seconds
    while time.monotonic() < deadline:
        time.sleep(max(0.05, min(1.0, deadline - time.monotonic())))
        print(f"qps={recorder.qps():.0f} avg={recorder.latency():.0f}us "
              f"p99={recorder.latency_percentile(0.99):.0f}us "
              f"max={recorder.max_latency():.0f}us "
              f"errors={error_count.get_value()}")
    stop.set()
    for t in threads:
        t.join()
    total = recorder.count()
    print(f"total={total} errors={error_count.get_value()}")
    srv.stop()
    return 0 if total > 0 and error_count.get_value() == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
