#!/usr/bin/env python
"""memcache_kv — example/memcache_c++ counterpart: batched memcache
binary-protocol operations through a memcache channel (memcache.h's
MemcacheRequest/Response batching).

  python examples/memcache_kv.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.memcache import (  # noqa: E402
    MemcacheRequest,
    MemcacheResponse,
    MemcacheService,
)


def main():
    srv = rpc.Server(rpc.ServerOptions(memcache_service=MemcacheService()))
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(protocol="memcache",
                                        timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0

    req = MemcacheRequest().set("chip", "tpu-v5e").get("chip") \
                           .incr("hits", 1, initial=1).incr("hits", 1)
    resp = MemcacheResponse()
    cntl = rpc.Controller()
    ch.call_method("memcache", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.pop_store()
    ok, value = resp.pop_get()
    print(f"get chip -> {value!r}")
    _, first = resp.pop_counter()
    _, second = resp.pop_counter()
    print(f"hits counter: {first} then {second}")
    ch.close()
    srv.stop()
    return 0 if ok and value == b"tpu-v5e" and second == first + 1 else 1


if __name__ == "__main__":
    sys.exit(main())
