#!/usr/bin/env python
"""device_performance — the example/rdma_performance twin: a
bvar-instrumented client/server pair hammering the device-transport lane
with concurrent pushers, reporting qps / latency percentiles / achieved
bandwidth from LatencyRecorders the way rdma_performance's client does
(client.cpp:50-52,136-183: g_latency_recorder + bvar reads per second).

  python examples/device_performance.py [--threads 2] [--mb 2] [--iters 8]
"""
import argparse
import sys
import threading
import time

sys.path.insert(0, ".")

import _jaxenv  # noqa: E402

_jaxenv.apply()

import numpy as np  # noqa: E402

from brpc_tpu import bvar, rpc  # noqa: E402
from brpc_tpu.rpc import device_transport as dt  # noqa: E402
from brpc_tpu.rpc.tensor_service import (  # noqa: E402
    TensorClient,
    TensorStoreService,
    make_device_channel,
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=2)
    ap.add_argument("--mb", type=int, default=2)
    ap.add_argument("--iters", type=int, default=8)
    args = ap.parse_args()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(TensorStoreService())
    assert srv.start("127.0.0.1:0") == 0
    target = str(srv.listen_endpoint)

    recorder = bvar.LatencyRecorder("device_perf")
    bytes_moved = bvar.Adder("device_perf_bytes")
    errors = bvar.Adder("device_perf_errors")
    payload = np.random.default_rng(0).standard_normal(
        (args.mb * 1024 * 1024) // 8).astype(np.float64)

    def pusher(tid: int):
        ch = make_device_channel(target)
        client = TensorClient(ch)
        for i in range(args.iters):
            t0 = time.perf_counter()
            cntl, resp = client.push(f"t{tid}.{i}", [payload])
            if cntl.failed() or not resp.ok:
                errors.update(1)
                continue
            recorder.update((time.perf_counter() - t0) * 1e6)
            bytes_moved.update(payload.nbytes)
        ch.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=pusher, args=(t,))
               for t in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    total = bytes_moved.get_value()
    counters = dt.lane_counters()
    lane = max(counters, key=counters.get)
    print(f"lane={lane} pushes={recorder.count()} "
          f"errors={errors.get_value()}")
    print(f"avg={recorder.latency():.0f}us "
          f"p99={recorder.latency_percentile(0.99):.0f}us "
          f"max={recorder.max_latency():.0f}us")
    print(f"throughput={total / wall / 1e9:.2f} GB/s "
          f"({total / 1e6:.0f} MB in {wall:.2f}s)")
    srv.stop()

    # The NATIVE bulk data path (streamed attachments through the C++
    # runtime: socket write queue -> dispatcher -> zero-copy echo): the
    # large-payload throughput of the native port, reported alongside
    # the Python-lane number above.
    try:
        import ctypes

        from brpc_tpu import native

        if native.available():
            lib = native.load()
            lib.nat_rpc_client_bench_bulk.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                ctypes.c_double, ctypes.POINTER(ctypes.c_uint64)]
            lib.nat_rpc_client_bench_bulk.restype = ctypes.c_double
            port = native.rpc_server_start(native_echo=True)
            try:
                moved = ctypes.c_uint64(0)
                gbps = lib.nat_rpc_client_bench_bulk(
                    b"127.0.0.1", port, args.mb << 20, 1.5,
                    ctypes.byref(moved))
                print(f"native_bulk={gbps:.2f} GB/s "
                      f"({moved.value / 1e6:.0f} MB echoed, "
                      f"{args.mb}MB attachments)")
            finally:
                native.rpc_server_stop()
    except Exception as e:
        print(f"native bulk lane unavailable: {e}")
    return 0 if recorder.count() > 0 and errors.get_value() == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
