"""Native protocol CLIENT lanes tour: the same C++ client machinery that
speaks tpu_std also speaks HTTP/1.1 and h2/gRPC (nat_client.cpp — the
client half of policy/http_rpc_protocol.cpp / http2_rpc_protocol.cpp).
One server port answers all three through the native runtime.

Run: python examples/native_protocol_clients.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import native, rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def main():
    if not native.available():
        print("native toolchain unavailable; nothing to demo")
        return

    srv = rpc.Server(rpc.ServerOptions(num_threads=2,
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    print(f"native multi-protocol server on 127.0.0.1:{port}")

    # 1. gRPC through the native h2 client (preface + HPACK + flow
    #    control in C++; works against stock grpcio servers too)
    g = native.channel_open_grpc("127.0.0.1", port)
    req = echo_pb2.EchoRequest(message="over-h2")
    status, body, msg = native.grpc_call(g, "/EchoService/Echo",
                                         req.SerializeToString(),
                                         timeout_ms=5000)
    reply = echo_pb2.EchoResponse.FromString(body)
    print(f"grpc: status={status} reply={reply.message!r}")
    assert status == 0 and reply.message == "over-h2"
    native.channel_close(g)

    # 2. HTTP/1.1 through the native client (pipelined FIFO correlation)
    h = native.channel_open_http("127.0.0.1", port)
    code, body = native.http_call(h, "GET", "/health", timeout_ms=5000)
    print(f"http GET /health: {code} {body!r}")
    assert code == 200
    code, body = native.http_call(
        h, "POST", "/EchoService/Echo",
        body=b'{"message": "over-http"}',
        headers="Content-Type: application/json\r\n", timeout_ms=5000)
    print(f"http POST echo: {code} {body!r}")
    assert code == 200 and b"over-http" in body
    native.channel_close(h)

    srv.stop()
    print("ok")


if __name__ == "__main__":
    main()
