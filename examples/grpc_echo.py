#!/usr/bin/env python
"""grpc_echo — example/grpc_c++ counterpart: the same service answers our
native tpu_std protocol AND gRPC-over-h2 on one port.

  python examples/grpc_echo.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with rpc.ClosureGuard(done):
            response.message = request.message


def main():
    srv = rpc.Server()
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    target = str(srv.listen_endpoint)

    gch = rpc.Channel(rpc.ChannelOptions(protocol="h2:grpc",
                                         timeout_ms=3000))
    assert gch.init(target) == 0
    cntl, resp = gch.call("EchoService.Echo",
                          echo_pb2.EchoRequest(message="over grpc"),
                          echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    print(f"grpc reply: {resp.message!r} latency={cntl.latency_us:.0f}us")
    gch.close()

    nch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1000))
    assert nch.init(target) == 0
    cntl2, resp2 = nch.call("EchoService.Echo",
                            echo_pb2.EchoRequest(message="over tpu_std"),
                            echo_pb2.EchoResponse)
    assert not cntl2.failed(), cntl2.error_text
    print(f"tpu_std reply on the same port: {resp2.message!r}")
    nch.close()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
