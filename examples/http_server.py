#!/usr/bin/env python
"""http — RESTful access + console (example/http_c++ counterpart): the same
service answers tpu_std RPC, JSON-over-HTTP, and serves the builtin
console on one port (brpc's multi-protocol port).

  python examples/http_server.py          # demo: curl-style requests
  python examples/http_server.py serve    # keep serving on :8000
"""
import http.client
import json
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = f"http says: {request.message}"
        done()


def main():
    serve = len(sys.argv) > 1 and sys.argv[1] == "serve"
    srv = rpc.Server()
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:8000" if serve else "127.0.0.1:0") == 0
    print(f"serving on {srv.listen_endpoint} — try:")
    print(f"  curl http://{srv.listen_endpoint}/status")
    print(f"  curl -d '{{\"message\":\"hi\"}}' "
          f"http://{srv.listen_endpoint}/EchoService/Echo")
    if serve:
        srv.run_until_asked_to_quit()
        return

    conn = http.client.HTTPConnection("127.0.0.1",
                                      srv.listen_endpoint.port, timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=json.dumps({"message": "from-curl"}),
                 headers={"Content-Type": "application/json"})
    print("JSON RPC:", conn.getresponse().read().decode())
    conn.request("GET", "/status")
    print("console /status:\n", conn.getresponse().read().decode()[:400])
    conn.close()
    srv.stop()


if __name__ == "__main__":
    main()
