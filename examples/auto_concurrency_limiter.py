#!/usr/bin/env python
"""auto_concurrency_limiter — adaptive admission control
(example/auto_concurrency_limiter counterpart): a server with method
max_concurrency="auto" sheds load under a burst; the limiter re-sizes from
measured qps and no-load latency.

  python examples/auto_concurrency_limiter.py
"""
import sys
import threading
import time

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc import errors  # noqa: E402
from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class WorkService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Work(self, cntl, request, response, done):
        time.sleep(0.01)  # 10ms of "work"
        response.message = "done"
        done()


def main():
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=8, method_max_concurrency={"WorkService.Work": "auto"}))
    srv.add_service(WorkService())
    assert srv.start("127.0.0.1:0") == 0

    status = srv.method_statuses()["WorkService.Work"]
    ok = [0]
    rejected = [0]
    lock = threading.Lock()

    def client(n):
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=2000))
        ch.init(str(srv.listen_endpoint))
        for _ in range(n):
            cntl, _ = ch.call("WorkService.Work",
                              echo_pb2.EchoRequest(message="w"),
                              echo_pb2.EchoResponse)
            with lock:
                if cntl.failed() and cntl.error_code == errors.ELIMIT:
                    rejected[0] += 1
                elif not cntl.failed():
                    ok[0] += 1

    threads = [threading.Thread(target=client, args=(30,)) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    print(f"ok={ok[0]} rejected={rejected[0]} "
          f"final_limit={status.limiter.max_concurrency()}")
    srv.stop()


if __name__ == "__main__":
    main()
