#!/usr/bin/env python
"""redis_kv — example/redis_c++ counterpart: the server SPEAKS redis (a
RedisService with command handlers, redis.h's server side) and the client
pipelines commands over a redis channel; vanilla redis-cli works too.

  python examples/redis_kv.py
"""
import sys

sys.path.insert(0, ".")

from brpc_tpu import rpc  # noqa: E402
from brpc_tpu.rpc.redis import (  # noqa: E402
    DictRedisService,
    RedisRequest,
    RedisResponse,
)


def main():
    srv = rpc.Server(rpc.ServerOptions(redis_service=DictRedisService()))
    assert srv.start("127.0.0.1:0") == 0

    ch = rpc.Channel(rpc.ChannelOptions(protocol="redis", timeout_ms=1000))
    assert ch.init(str(srv.listen_endpoint)) == 0

    req = RedisRequest()
    req.add_command("SET", "pod", "v5e-8")
    req.add_command("GET", "pod")
    req.add_command("DEL", "pod")
    req.add_command("GET", "pod")
    resp = RedisResponse()
    cntl = rpc.Controller()
    ch.call_method("redis", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.reply_count == 4
    print("SET ->", resp.reply(0))
    print("GET ->", resp.reply(1))
    print("DEL ->", resp.reply(2))
    print("GET after DEL ->", resp.reply(3), "(nil)" if
          resp.reply(3).is_nil() else "")
    ch.close()
    srv.stop()
    return 0 if resp.reply(1).value == b"v5e-8" else 1


if __name__ == "__main__":
    sys.exit(main())
